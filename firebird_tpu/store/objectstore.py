"""Object-store-native durable tier (ROADMAP item 4, ISSUE 19).

Everything durable in this repo historically lived in files next to the
store (sqlite shards, ``fleet.db``, the packed ``.fbss`` statestore,
``.npy`` pyramid tiles) — which welds replicas to one disk.  This module
is the one storage plane under all of them: a minimal **ObjectStore
protocol** (``put/get/list/delete/head`` plus a *conditional put keyed on
object generation*) with content-addressed chunking and a
manifest-commit publish step, so a multi-chunk upload is invisible until
one atomic final write lands.

Layout of the local-directory reference implementation::

    <root>/chunks/<sha256>                      content-addressed chunks
    <root>/keys/<quoted-key>/g<N>.json          per-generation manifests
    <root>/keys/<quoted-key>/.lock              conditional-put lock

Invariants the chaos soak (tools/objectstore_chaos.py) pins:

- **Atomic publish.** Chunks upload first; the object only becomes
  visible when its manifest commits via tmp+rename.  A SIGKILL between
  the last chunk upload and the manifest commit leaves *no visible
  object* — just orphaned chunks that ``scrub`` reclaims after a grace
  window (never sooner, so a live writer's not-yet-committed chunks
  survive the scrub race).
- **Conditional put.** ``put(key, data, if_generation=g)`` succeeds only
  if the newest committed generation is exactly ``g`` (``0`` = the key
  must not exist).  Losers get :class:`PreconditionFailed` — a
  :class:`~firebird_tpu.retry.NonRetryable`, so retry wrappers re-raise
  instead of burning budget on a race they already lost.
- **Generation fallback.** The last two generations are retained (the
  object-tier analogue of the statestore's double-bank slots).  ``get``
  verifies every chunk's sha256+size against the manifest and falls
  back one generation on a torn newest — exactly the ``.fbss`` torn-slot
  recovery contract (``objectstore_torn_recoveries`` counts it).
- **Fencing at the object layer.** :class:`ObjectBackedStore` stamps the
  fleet fencing token into each shard's manifest metadata; a zombie
  whose fence is older than the stored one is rejected *before any
  bytes land* (:class:`StaleObjectFence`, counted durably in the
  ``_meta/fence_rejects`` object and by ``object_fence_rejected_total``).

Every operation is fault-injectable (``faults.py`` ``object`` scope,
including the ``torn`` kind that commits a truncated chunk or drops the
manifest write) and routes through ``retry.RetryPolicy.for_object`` with
the shared budget/breaker (:func:`open_object_root`).
"""

from __future__ import annotations

import base64
import dataclasses
import fcntl
import hashlib
import json
import os
import threading
import time
import urllib.parse

import numpy as np

from firebird_tpu import retry as retrylib
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.store import schema
from firebird_tpu.store.backends import _col_types, _normalize

# Retained generations per key: newest + one fallback — the double-bank
# contract (statestore.py slot banks) lifted to the object tier.
KEEP_GENERATIONS = 2

DEFAULT_CHUNK_SIZE = 256 * 1024


class ObjectStoreError(OSError):
    """Base for object-tier failures (transient unless subclassed)."""


class PreconditionFailed(ObjectStoreError, retrylib.NonRetryable):
    """Conditional put lost the generation race.

    NonRetryable: replaying the same put can never succeed — the caller
    must re-read and merge, not spend retry budget.
    """

    def __init__(self, msg: str, current: int = -1):
        super().__init__(msg)
        self.current = current


class StaleObjectFence(ObjectStoreError, retrylib.NonRetryable):
    """A zombie's write arrived with a fencing token older than one
    already stamped on the object — rejected before any bytes landed."""


@dataclasses.dataclass(frozen=True)
class ObjectMeta:
    """head() result: the committed manifest, minus the bytes."""

    key: str
    generation: int
    size: int
    chunks: tuple  # ((sha256, size), ...)
    meta: dict
    updated: float


class LocalObjectStore:
    """Local-directory reference implementation of the protocol.

    Process- and thread-safe: conditional puts serialize on a per-key
    ``fcntl`` lock file, chunk and manifest writes are tmp+rename (both
    idempotent — chunks are content-addressed, manifests are
    per-generation), and readers never take the lock.
    """

    def __init__(self, root: str, chunk_size: int = DEFAULT_CHUNK_SIZE):
        self.root = root
        self.chunk_size = max(int(chunk_size), 1)
        self._chunk_dir = os.path.join(root, "chunks")
        self._key_dir = os.path.join(root, "keys")
        os.makedirs(self._chunk_dir, exist_ok=True)
        os.makedirs(self._key_dir, exist_ok=True)
        self._lock = threading.Lock()  # serialize same-process putters

    # -- key <-> directory mapping ---------------------------------------

    def _kdir(self, key: str) -> str:
        return os.path.join(self._key_dir,
                            urllib.parse.quote(key, safe=""))

    @staticmethod
    def _unq(name: str) -> str:
        return urllib.parse.unquote(name)

    def _generations(self, kdir: str) -> list[int]:
        """Committed generation numbers for a key, newest first."""
        try:
            names = os.listdir(kdir)
        except OSError:
            return []
        gens = []
        for n in names:
            if n.startswith("g") and n.endswith(".json"):
                try:
                    gens.append(int(n[1:-5]))
                except ValueError:
                    continue
        return sorted(gens, reverse=True)

    def _manifest(self, kdir: str, gen: int) -> dict | None:
        try:
            with open(os.path.join(kdir, f"g{gen}.json"), "rb") as f:
                m = json.loads(f.read())
        except (OSError, ValueError):
            return None
        if not isinstance(m, dict) or "chunks" not in m:
            return None
        return m

    @staticmethod
    def _meta_of(key: str, gen: int, m: dict) -> ObjectMeta:
        return ObjectMeta(
            key=key, generation=gen, size=int(m.get("size", 0)),
            chunks=tuple((c[0], int(c[1])) for c in m["chunks"]),
            meta=dict(m.get("meta") or {}),
            updated=float(m.get("updated", 0.0)))

    # -- chunk plumbing ---------------------------------------------------

    def _chunk_path(self, sha: str) -> str:
        return os.path.join(self._chunk_dir, sha)

    def _put_chunk(self, sha: str, blob: bytes, force: bool = False):
        path = self._chunk_path(sha)
        if not force and os.path.exists(path):
            return  # content-addressed: identical bytes already landed
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _read_chunk(self, sha: str, size: int) -> bytes:
        with open(self._chunk_path(sha), "rb") as f:
            blob = f.read()
        if len(blob) != size or hashlib.sha256(blob).hexdigest() != sha:
            raise ObjectStoreError(
                f"chunk {sha[:12]} torn: {len(blob)} bytes vs manifest "
                f"{size}")
        return blob

    # -- the protocol -----------------------------------------------------

    def put(self, key: str, data: bytes, *, if_generation: int | None = None,
            meta: dict | None = None, _torn: str | None = None) -> ObjectMeta:
        """Publish ``data`` under ``key`` as generation N+1.

        ``if_generation`` makes the put conditional: it succeeds only
        when the newest committed generation equals it (0 = key must not
        exist); otherwise :class:`PreconditionFailed`.

        ``_torn`` is the fault-injection hatch (faults.py ``torn`` kind):
        ``"chunk"`` commits the manifest over a truncated final chunk,
        ``"manifest"`` uploads every chunk and drops the commit — the
        two halves of a torn multi-part upload.
        """
        data = bytes(data)
        chunks = []
        for off in range(0, max(len(data), 1), self.chunk_size):
            blob = data[off:off + self.chunk_size]
            sha = hashlib.sha256(blob).hexdigest()
            if _torn == "chunk" and off + self.chunk_size >= len(data):
                # Commit a truncated final chunk under the full-content
                # sha — the manifest will promise bytes that are not
                # there, which is exactly what readers must survive.
                self._put_chunk(sha, blob[:max(len(blob) - 1, 0)],
                                force=True)
            else:
                self._put_chunk(sha, blob)
            chunks.append((sha, len(blob)))

        if _torn == "manifest":
            # The upload dies before the commit: chunks are orphaned
            # debris for scrub; the object (this generation) never
            # becomes visible.
            return self.head(key) or ObjectMeta(key, 0, 0, (), {}, 0.0)

        from firebird_tpu.config import env_knob
        hold = float(env_knob("FIREBIRD_OBJECT_COMMIT_HOLD_SEC") or 0)
        if hold > 0:
            # Chaos hook: widen the chunk-upload -> manifest-commit
            # window so a SIGKILL can land inside it deterministically.
            time.sleep(hold)

        kdir = self._kdir(key)
        os.makedirs(kdir, exist_ok=True)
        with self._lock, open(os.path.join(kdir, ".lock"), "a+") as lk:
            fcntl.lockf(lk, fcntl.LOCK_EX)
            gens = self._generations(kdir)
            cur = gens[0] if gens else 0
            if if_generation is not None and cur != if_generation:
                obs_metrics.counter(
                    "objectstore_conflicts",
                    help="conditional puts that lost the generation race"
                ).inc()
                raise PreconditionFailed(
                    f"put {key!r}: expected generation {if_generation}, "
                    f"found {cur}", current=cur)
            gen = cur + 1
            m = {"key": key, "generation": gen, "size": len(data),
                 "chunks": chunks, "meta": dict(meta or {}),
                 "updated": time.time()}
            path = os.path.join(kdir, f"g{gen}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(json.dumps(m).encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            for old in gens[KEEP_GENERATIONS - 1:]:
                try:
                    os.unlink(os.path.join(kdir, f"g{old}.json"))
                except OSError:
                    pass
        obs_metrics.counter(
            "objectstore_puts",
            help="objects published (manifest commits)").inc()
        return self._meta_of(key, gen, m)

    def get(self, key: str) -> tuple[bytes, ObjectMeta]:
        """Newest verifiable generation's bytes.

        Every chunk is checked against the manifest's sha256+size; a
        torn newest generation falls back one generation — the same
        recovery the packed statestore's double-bank CRC slots give."""
        kdir = self._kdir(key)
        gens = self._generations(kdir)
        if not gens:
            raise KeyError(f"object {key!r} does not exist")
        last_err: Exception | None = None
        for i, gen in enumerate(gens):
            m = self._manifest(kdir, gen)
            if m is None:
                continue
            try:
                data = b"".join(self._read_chunk(sha, size)
                                for sha, size in m["chunks"])
            except OSError as e:
                last_err = e
                continue
            if i > 0:
                obs_metrics.counter(
                    "objectstore_torn_recoveries",
                    help=("reads that fell back a generation past a "
                          "torn newest object")).inc()
            obs_metrics.counter("objectstore_gets",
                                help="object reads served").inc()
            return data, self._meta_of(key, gen, m)
        raise ObjectStoreError(
            f"object {key!r}: no verifiable generation "
            f"(newest error: {last_err})")

    def head(self, key: str) -> ObjectMeta | None:
        kdir = self._kdir(key)
        for gen in self._generations(kdir):
            m = self._manifest(kdir, gen)
            if m is not None:
                return self._meta_of(key, gen, m)
        return None

    def list(self, prefix: str = "") -> list[str]:
        try:
            names = os.listdir(self._key_dir)
        except OSError:
            return []
        out = []
        for n in sorted(names):
            key = self._unq(n)
            if key.startswith(prefix) and self._generations(
                    os.path.join(self._key_dir, n)):
                out.append(key)
        return out

    def delete(self, key: str) -> None:
        """Drop every generation of a key (chunks become scrub debris)."""
        kdir = self._kdir(key)
        try:
            names = os.listdir(kdir)
        except OSError:
            return
        for n in names:
            try:
                os.unlink(os.path.join(kdir, n))
            except OSError:
                pass
        try:
            os.rmdir(kdir)
        except OSError:
            pass

    # -- maintenance ------------------------------------------------------

    def _referenced(self) -> set[str]:
        refs: set[str] = set()
        try:
            names = os.listdir(self._key_dir)
        except OSError:
            return refs
        for n in names:
            kdir = os.path.join(self._key_dir, n)
            for gen in self._generations(kdir):
                m = self._manifest(kdir, gen)
                if m:
                    refs.update(sha for sha, _ in m["chunks"])
        return refs

    def scrub(self, grace_sec: float = 60.0, dry_run: bool = False) -> dict:
        """Reclaim chunks unreferenced by any retained manifest.

        Only chunks older than ``grace_sec`` go — a live writer's
        chunks-uploaded-manifest-pending window is younger than any sane
        grace, so the scrub-vs-live-writer race resolves to "keep"."""
        refs = self._referenced()
        now = time.time()
        removed = kept_young = 0
        try:
            names = os.listdir(self._chunk_dir)
        except OSError:
            names = []
        for n in names:
            if n in refs:
                continue
            path = os.path.join(self._chunk_dir, n)
            try:
                age = now - os.stat(path).st_mtime
            except OSError:
                continue
            if age < grace_sec:
                kept_young += 1
                continue
            if not dry_run:
                try:
                    os.unlink(path)
                except OSError:
                    continue
            removed += 1
        if removed and not dry_run:
            obs_metrics.counter(
                "objectstore_scrubbed_chunks",
                help="orphaned chunks reclaimed by the scrubber"
            ).inc(removed)
        return {"removed": removed, "kept_young": kept_young,
                "referenced": len(refs), "dry_run": bool(dry_run)}

    def census(self) -> dict:
        """Key/manifest/chunk/orphan counts — never raises (the status
        view must degrade honestly on a corrupt root, not crash)."""
        out = {"root": self.root, "keys": 0, "manifests": 0, "chunks": 0,
               "orphan_chunks": 0, "chunk_bytes": 0, "junk": 0}
        refs: set[str] = set()
        try:
            names = os.listdir(self._key_dir)
        except OSError as e:
            out["error"] = f"{type(e).__name__}: {e}"
            return out
        for n in names:
            kdir = os.path.join(self._key_dir, n)
            gens = self._generations(kdir)
            parsed = 0
            for gen in gens:
                m = self._manifest(kdir, gen)
                if m is None:
                    out["junk"] += 1
                    continue
                parsed += 1
                refs.update(sha for sha, _ in m["chunks"])
            if parsed:
                out["keys"] += 1
                out["manifests"] += parsed
            elif gens:
                out["junk"] += 1
        try:
            chunk_names = os.listdir(self._chunk_dir)
        except OSError as e:
            out["error"] = f"{type(e).__name__}: {e}"
            return out
        for n in chunk_names:
            if n.endswith(".tmp") or ".tmp." in n:
                out["junk"] += 1
                continue
            out["chunks"] += 1
            try:
                out["chunk_bytes"] += os.stat(
                    os.path.join(self._chunk_dir, n)).st_size
            except OSError:
                pass
            if n not in refs:
                out["orphan_chunks"] += 1
        return out

    def close(self) -> None:
        pass


class RetryingObjectStore:
    """Every object operation through one shared ``RetryPolicy``.

    Transient injected faults (ioerror/timeout/conn) heal inline under
    the run's budget/breaker; :class:`PreconditionFailed`,
    :class:`StaleObjectFence`, and the torn kind are NonRetryable and
    surface immediately (a lost race or a torn upload is a fact, not a
    blip)."""

    def __init__(self, inner, policy: retrylib.RetryPolicy):
        self._inner = inner
        self._policy = policy
        import logging
        self._log = logging.getLogger("firebird.objectstore")

    def _run(self, what: str, fn):
        return self._policy.run(self._log, what, fn)

    def put(self, key, data, **kw):
        return self._run(f"object put {key}",
                         lambda: self._inner.put(key, data, **kw))

    def get(self, key):
        return self._run(f"object get {key}", lambda: self._inner.get(key))

    def head(self, key):
        return self._run(f"object head {key}", lambda: self._inner.head(key))

    def list(self, prefix=""):
        return self._run(f"object list {prefix!r}",
                         lambda: self._inner.list(prefix))

    def delete(self, key):
        return self._run(f"object delete {key}",
                         lambda: self._inner.delete(key))

    def __getattr__(self, name):
        return getattr(self._inner, name)


def cas_update(store, key: str, fn, attempts: int = 64) -> bytes:
    """Atomic read-modify-write of one object via conditional put.

    ``fn(old_bytes_or_None) -> new_bytes``; loops on
    :class:`PreconditionFailed` (somebody else won the generation race —
    re-read and reapply)."""
    for _ in range(attempts):
        h = store.head(key)
        if h is None:
            old, gen = None, 0
        else:
            # Precondition on head()'s newest committed generation, not
            # get()'s — a torn newest makes get fall back a generation,
            # and a put conditioned on the fallback gen can never land.
            old, _ = store.get(key)
            gen = h.generation
        try:
            new = fn(old)
            store.put(key, new, if_generation=gen)
            return new
        except PreconditionFailed:
            continue
    raise ObjectStoreError(
        f"cas_update {key!r}: lost the generation race {attempts} times")


def scope_for_path(path: str) -> str:
    """Stable per-store key-prefix scope, so two runs pointing different
    local store paths at ONE object root never collide (the chaos soak's
    clean and chaos legs share a root by design)."""
    return hashlib.sha256(
        os.path.abspath(path).encode()).hexdigest()[:12]


# -- the Store facade ------------------------------------------------------

# Shard partitioning: leading primary-key columns per table — the same
# one-file-per-chip rule ParquetStore uses (backends.ParquetStore._PART),
# so a chip rerun rewrites exactly its own shard.
_PART = {"chip": 2, "pixel": 2, "segment": 2, "tile": 3, "product": 4}


def _encode_cell_json(v, typ: str):
    """One cell -> JSON-safe wire value: packed arrays base64, scalars
    normalized NaN->None, JSON columns stay structured."""
    if typ in schema.PACKED_DTYPES:
        if v is None:
            return None
        return base64.b64encode(
            np.asarray(v, schema.PACKED_DTYPES[typ]).tobytes()).decode()
    return _normalize(v)


def _decode_cell_json(v, typ: str):
    """Inverse of :func:`_encode_cell_json`, matching SqliteStore's
    decoded cell values (packed columns come back as plain lists)."""
    if v is None:
        return None
    if typ in schema.PACKED_DTYPES:
        return np.frombuffer(base64.b64decode(v),
                             schema.PACKED_DTYPES[typ]).tolist()
    return v


class ObjectBackedStore:
    """The Store interface (write/read/count/chip_ids) over ObjectStore.

    One object per (table, partition-key prefix) shard; the shard body
    is a JSON document of rows keyed by primary key, merged under a
    conditional-put loop so concurrent writers to one shard serialize on
    generations instead of clobbering.

    ``bind_fence`` stamps the fleet fencing token into every shard's
    manifest metadata; a staler writer is rejected at the object layer
    (:class:`StaleObjectFence`) before any row lands, and the rejection
    is counted durably in the scope's ``_meta/fence_rejects`` object.
    """

    FENCE_REJECTS_KEY = "_meta/fence_rejects"

    def __init__(self, objstore, scope: str, keyspace: str = "default",
                 read_only: bool = False):
        self._obj = objstore
        self.keyspace = keyspace
        self.read_only = bool(read_only)
        self._prefix = f"{scope}/{keyspace}"
        self._fence: int | None = None

    # -- fencing ----------------------------------------------------------

    def bind_fence(self, fence: int) -> None:
        """Arm object-layer fencing: every subsequent write carries this
        token and refuses to land under a newer one (FencedStore calls
        this at construction, fleet/queue.py)."""
        self._fence = int(fence)

    def _record_fence_reject(self, table: str, stored: int) -> None:
        def bump(old):
            d = json.loads(old) if old else {"total": 0}
            d["total"] = int(d.get("total", 0)) + 1
            d[f"table_{table}"] = int(d.get(f"table_{table}", 0)) + 1
            return json.dumps(d).encode()

        cas_update(self._obj, f"{self._prefix}/{self.FENCE_REJECTS_KEY}",
                   bump)
        obs_metrics.counter(
            "object_fence_rejected_total",
            help=("stale-fence conditional puts rejected at the "
                  "object layer")).inc()

    def fence_rejects(self) -> int:
        """Durable count of object-layer stale-fence rejections for this
        store scope (the chaos soak's proof the zombie never landed)."""
        try:
            data, _ = self._obj.get(
                f"{self._prefix}/{self.FENCE_REJECTS_KEY}")
        except KeyError:
            return 0
        return int(json.loads(data).get("total", 0))

    # -- shard plumbing ---------------------------------------------------

    def _shard_key(self, table: str, part: tuple) -> str:
        pid = "_".join(str(p) for p in part)
        return f"{self._prefix}/{table}/{pid}"

    @staticmethod
    def _row_key(row: dict, pk: tuple) -> str:
        return json.dumps([_normalize(row[k]) for k in pk])

    def write(self, table: str, frame: dict) -> int:
        if self.read_only:
            raise RuntimeError(
                f"write to {table!r} on a read-only object-store handle")
        types = _col_types(table)
        pk = schema.primary_key(table)
        keyp = pk[:_PART[table]]
        n = len(next(iter(frame.values())))
        # Encode once, then group rows by partition shard.
        rows: dict[tuple, dict[str, dict]] = {}
        for i in range(n):
            row = {c: _encode_cell_json(frame[c][i], types[c])
                   for c in types if c in frame}
            part = tuple(_normalize(frame[k][i]) for k in keyp)
            rk = json.dumps([_normalize(frame[k][i]) for k in pk])
            rows.setdefault(part, {})[rk] = row
        for part, newrows in rows.items():
            self._merge_shard(table, part, newrows)
        return n

    def _merge_shard(self, table: str, part: tuple,
                     newrows: dict[str, dict]) -> None:
        key = self._shard_key(table, part)
        while True:
            h = self._obj.head(key)
            stored_fence = int(h.meta.get("fence", 0)) if h else 0
            if self._fence is not None and stored_fence > self._fence:
                # A successor already wrote with a newer token: this
                # handle is a zombie's.  Refuse before any bytes land.
                self._record_fence_reject(table, stored_fence)
                raise StaleObjectFence(
                    f"object write to {key!r} carries fence "
                    f"{self._fence} but generation {h.generation} was "
                    f"written under fence {stored_fence}; this writer "
                    "has been fenced off")
            merged = dict(newrows)
            if h is not None:
                # Merge against readable rows but condition the put on
                # head()'s generation — get() may have fallen back past
                # a torn newest, whose generation number still counts.
                data, _ = self._obj.get(key)
                doc = json.loads(data)
                merged = {**doc.get("rows", {}), **newrows}
            meta = {"rows": len(merged), "table": table}
            fence = max(stored_fence,
                        self._fence if self._fence is not None else 0)
            if fence:
                meta["fence"] = fence
            body = json.dumps({"table": table, "rows": merged}).encode()
            try:
                self._obj.put(key, body, meta=meta,
                              if_generation=h.generation if h else 0)
                return
            except PreconditionFailed:
                continue  # another writer won this generation: re-merge

    # -- reads ------------------------------------------------------------

    def _shards(self, table: str) -> list[str]:
        return self._obj.list(f"{self._prefix}/{table}/")

    def read(self, table: str, where: dict | None = None) -> dict:
        types = _col_types(table)
        cols = list(types)
        keyp = schema.primary_key(table)[:_PART[table]]
        if where and all(k in where for k in keyp):
            part = tuple(_normalize(where[k]) for k in keyp)
            skey = self._shard_key(table, part)
            keys = [skey] if self._obj.head(skey) is not None else []
        else:
            keys = self._shards(table)
        out: dict[str, list] = {c: [] for c in cols}
        for skey in keys:
            try:
                data, _ = self._obj.get(skey)
            except KeyError:
                continue
            for row in json.loads(data).get("rows", {}).values():
                vals = {c: _decode_cell_json(row.get(c), types[c])
                        for c in cols}
                if where and any(vals.get(k) != _normalize(v)
                                 for k, v in where.items()):
                    continue
                for c in cols:
                    out[c].append(vals[c])
        return out

    def count(self, table: str) -> int:
        # Head-only: row counts ride shard manifest metadata.
        total = 0
        for skey in self._shards(table):
            h = self._obj.head(skey)
            if h is not None:
                total += int(h.meta.get("rows", 0))
        return total

    def chip_ids(self, table: str = "segment") -> set[tuple[int, int]]:
        k1, k2 = schema.primary_key(table)[:2]
        out: set[tuple[int, int]] = set()
        for skey in self._shards(table):
            try:
                data, _ = self._obj.get(skey)
            except KeyError:
                continue
            for rk in json.loads(data).get("rows", {}):
                kv = json.loads(rk)
                out.add((kv[0], kv[1]))
        return out

    def close(self) -> None:
        close = getattr(self._obj, "close", None)
        if close is not None:
            close()


class MirroredStore:
    """Write-through mirror: a local Store stays read-authoritative,
    every durable write ALSO publishes to the object tier — **object
    first**, so a zombie's stale write is rejected at the object layer
    before a single local byte lands (``make fleet-smoke`` with
    ``FIREBIRD_OBJECT_ROOT`` set runs every write through here)."""

    def __init__(self, local, mirror: ObjectBackedStore):
        self._local = local
        self._mirror = mirror

    def bind_fence(self, fence: int) -> None:
        self._mirror.bind_fence(fence)

    def write(self, table: str, frame: dict) -> int:
        self._mirror.write(table, frame)
        return self._local.write(table, frame)

    def fence_rejects(self) -> int:
        return self._mirror.fence_rejects()

    @property
    def object_mirror(self) -> ObjectBackedStore:
        return self._mirror

    def close(self) -> None:
        try:
            self._mirror.close()
        finally:
            self._local.close()

    def __getattr__(self, name):
        return getattr(self._local, name)


# -- wiring ----------------------------------------------------------------

def open_object_root(root: str | None = None, cfg=None):
    """One fully-wired object root: LocalObjectStore under the run's
    fault plan (``object`` scope) under ``RetryPolicy.for_object`` with
    the shared budget semantics.  ``cfg=None`` reads the environment
    (the route every existing ``open_store`` call site inherits)."""
    from firebird_tpu.config import Config
    if cfg is None:
        cfg = Config.from_env()
    root = root or cfg.object_root
    if not root:
        raise ValueError(
            "open_object_root: no object root (set FIREBIRD_OBJECT_ROOT "
            "or pass root=)")
    store = LocalObjectStore(
        root, chunk_size=int(cfg.object_chunk_kb) * 1024)
    from firebird_tpu import faults as faultslib
    plan = faultslib.FaultPlan.parse(cfg.faults)
    if plan is not None:
        store = faultslib.wrap_objectstore(store, plan)
    return RetryingObjectStore(store, retrylib.RetryPolicy.for_object(cfg))
