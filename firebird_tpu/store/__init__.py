"""Keyed, idempotent result sinks.

Replaces the reference's Cassandra persistence (ccdc/cassandra.py + the
chip/pixel/segment/tile table modules + resources/schema.cql) with the same
four logical tables over pluggable backends.  The durability model is
preserved: primary keys are the natural keys, writes are upserts, so any
rerun of a tile/chunk overwrites the same rows (SURVEY.md §5
"checkpoint/resume = idempotent append writes").

Backends: sqlite (dev/default), parquet (bulk/analytics), memory (tests),
cassandra (production parity with the reference — needs cassandra-driver
or an injected session).

Writes are drained by an AsyncWriter on a host thread so device compute
overlaps egress (the reference instead tuned spark-cassandra concurrent
writes, ccdc/__init__.py:20-22).
"""

from firebird_tpu.store.schema import TABLES, primary_key
from firebird_tpu.store.backends import (CassandraStore, MemoryStore,
                                         ParquetStore, SqliteStore,
                                         cassandra_ddl, open_store)
from firebird_tpu.store.objectstore import (LocalObjectStore,
                                            MirroredStore,
                                            ObjectBackedStore,
                                            ObjectStoreError,
                                            PreconditionFailed,
                                            StaleObjectFence,
                                            open_object_root)
from firebird_tpu.store.writer import AsyncWriter

__all__ = ["TABLES", "primary_key", "CassandraStore", "MemoryStore",
           "SqliteStore", "ParquetStore", "cassandra_ddl", "open_store",
           "LocalObjectStore", "ObjectBackedStore", "MirroredStore",
           "ObjectStoreError", "PreconditionFailed", "StaleObjectFence",
           "open_object_root", "AsyncWriter"]
