"""firebird_tpu — a TPU-native LCMAP CCDC framework.

A ground-up re-design of the capabilities of USGS-EROS/lcmap-firebird
(reference: /root/reference, a PySpark 2.3 / Mesos / Cassandra driver for
per-pixel CCDC change detection + RandomForest land-cover classification)
for TPU hardware with JAX/XLA.

Architecture (vs. reference ccdc/ layering, see SURVEY.md §1):

- ``grid``      — pure-numpy Albers grid geometry (replaces merlin.geometry +
                  Chipmunk /grid /snap /near HTTP calls; ref ccdc/grid.py).
- ``ingest``    — chip sources + dense device packing (replaces merlin.create
                  + ccdc/timeseries.py per-pixel RDD fan-out).
- ``ccd``       — the CCDC science kernel in JAX (replaces the external
                  lcmap-pyccd package driven by ccdc/pyccd.py). NumPy float64
                  oracle + jit/vmap TPU kernel, scan-over-time design.
- ``rf``        — RandomForest training + JAX inference (replaces
                  ccdc/randomforest.py + features.py + udfs.py on Spark ML).
- ``store``     — keyed idempotent sinks: sqlite/parquet/memory backends with
                  the reference's four logical tables (replaces
                  ccdc/cassandra.py + chip/pixel/segment/tile modules).
- ``parallel``  — device mesh / sharding helpers (replaces Spark partitioning,
                  shuffle and Mesos scheduling with jax.sharding over ICI/DCN).
- ``driver``    — host orchestration: tile -> chunks -> prefetch -> device ->
                  drain (replaces ccdc/core.py).
- ``cli``       — `firebird changedetection|classification` (ref ccdc/cli.py).
- ``ops``       — Pallas TPU kernels for hot inner ops.
- ``utils``     — dates, functional helpers.

Unlike the reference (env vars read at import time, ccdc/__init__.py:11-26),
configuration here is explicit: build a :class:`firebird_tpu.config.Config`
and pass it down.
"""

from firebird_tpu.__about__ import __version__
from firebird_tpu.config import Config

__all__ = ["Config", "__version__"]
