# Dev / deploy automation, mirroring the reference's Makefile:24-56 target
# chain (docker-compose bring-up, db-schema load, tests) and the
# ccdc.install.example:86-94 run aliases — minus the Spark/Maven machinery
# the TPU runtime doesn't have.

COMPOSE := docker compose -f deploy/docker-compose.yml
# Tile example: CONUS Albers point inside tile h=20 v=11.
X ?= 542000
Y ?= 1650000
ACQUIRED ?= 1982-01-01/2017-12-31

.PHONY: install lint test bench obs-smoke pipeline-smoke chaos-smoke \
        fleet-smoke elastic-smoke serve-smoke pyramid-smoke serve-fleet \
        compact-smoke postmortem-smoke alert-smoke streamfleet-smoke \
        telemetry-smoke slo-smoke wire-smoke fuse-smoke fuse-repro \
        precision-smoke objectstore-smoke fanout-smoke fanout-proof \
        image db-up db-schema db-test db-down changedetection \
        classification clean

install:
	pip install -e . --no-build-isolation

# Static contract checker (docs/STATIC_ANALYSIS.md): the jax-hotpath,
# knob-registry, metrics-contract, and thread-ownership rule families
# over the repo itself.  Fails on findings NOT absorbed by the committed
# lint_baseline.json; the JSON summary lands in FIREBIRD_LINT_DIR
# (default /tmp/fb_lint) where bench.py folds it into round artifacts.
lint:
	python -m firebird_tpu.analysis \
	  --json "$${FIREBIRD_LINT_DIR:-/tmp/fb_lint}/lint_report.json"

# The default verify path runs the contract checker first: a knob/metric/
# hotpath/ownership drift fails the build before the (slower) test suite —
# then the alerting end-to-end drill (the smoke tier's representative:
# it exercises stream + serve + fleet queue together under chaos).
test: lint
	python -m pytest tests/ -x -q
	$(MAKE) pyramid-smoke
	$(MAKE) fuse-smoke
	$(MAKE) precision-smoke
	$(MAKE) alert-smoke
	$(MAKE) streamfleet-smoke
	$(MAKE) telemetry-smoke
	$(MAKE) slo-smoke
	$(MAKE) objectstore-smoke
	$(MAKE) fanout-smoke
	$(MAKE) elastic-smoke

bench:
	python bench.py

# End-to-end telemetry check: synthetic-source driver run with the span
# tracer on AND the ops endpoint bound to an ephemeral port — polls
# /healthz /readyz /metrics /progress while batches are in flight, then
# validates the emitted Chrome-trace JSON and obs_report.json against
# the schema + stage-key contract (docs/OBSERVABILITY.md).
obs-smoke:
	python tools/obs_smoke.py

# Zero-stall pipeline check: tiny end-to-end changedetection on CPU with
# input staging + bulk batch egress + the persistent compile cache on,
# twice — asserts the obs report carries the stage/egress histograms with
# nonzero counts and that run 2 hits the compile cache (no XLA recompile).
pipeline-smoke:
	python tools/pipeline_smoke.py

# Graceful-degradation check (docs/ROBUSTNESS.md): a synthetic tile run
# under a seeded fault plan (ingest p=0.05 + a poisoned chip + a store
# brownout), then `--resume` — asserts the poisoned chip lands in
# quarantine.json without failing its chunk, the quarantine drains, and
# the final store is row-for-row identical to a clean run.
chaos-smoke:
	python tools/chaos_soak.py

# Fleet-queue chaos check (docs/ROBUSTNESS.md "Fleet scheduling"): a
# multi-tile plan drained by worker subprocesses with one SIGKILLed
# mid-lease and one heartbeat-partitioned (lease:p=1 zombie) — asserts
# the survivors drain every job, the zombie's stale-fence writes are
# rejected (counter nonzero, zero accepted), and the merged store is
# row-identical to a clean single-worker run.
fleet-smoke:
	python tools/fleet_chaos.py

# Elastic-fleet chaos check (docs/ROBUSTNESS.md "Elastic operation"): a
# full 726-tile CONUS plan (tiny synthetic chips) drained by the
# autoscaling supervisor at 10x any prior soak's worker count, with
# random worker SIGKILLs, a heartbeat-partitioned zombie, and the
# supervisor itself killed + restarted mid-drain — asserts the restart
# ADOPTS orphaned workers (no double-spawn), every job drains, zero
# stale-fence writes are accepted (store row-identical to a clean
# serial leg), and the fleet scales back to zero afterwards.  The
# scale-decision log lands in the artifact (folded by bench.py).
elastic-smoke:
	python tools/elastic_soak.py

# Serving-layer check (docs/SERVING.md): tiny synthetic run into a
# sqlite store, then the query API on an ephemeral port — every endpoint
# exercised with values cross-checked against products.save output, 8
# concurrent identical cold misses proven to coalesce into ONE
# computation, cache hits proven, and the closed-loop loadtest artifact
# (RPS, p50/p95/p99, hit rate) written + folded by bench.py.
serve-smoke:
	python tools/serve_smoke.py

# Pyramid + changefeed coherence check (docs/SERVING.md): seed a store,
# build a 2-level quadkey pyramid — base tiles byte-compared against
# products.save rasters — then mutate one chip through the
# product_writes feed and assert EXACTLY the ancestor tiles go stale
# and the old ETag's 304 flips to a fresh 200; artifact folded by
# bench.py alongside the serve loadtest.
pyramid-smoke:
	python tools/pyramid_smoke.py

# Multi-replica read-path bench (docs/SERVING.md): seed + pyramid, then
# N `firebird serve` replicas (read-only mode=ro store connections)
# behind a round-robin front door under a mixed hot/cold/304/SSE
# workload from multi-process client shards, with a live writer
# mutating mid-test — the artifact carries aggregate RPS, p50/p95/p99,
# hit/304 rates, and max observed staleness vs the changefeed bound.
# Heavier than the smoke tier (spawns a process fleet), so not part of
# `make test`; bench.py folds the artifact when it exists.
serve-fleet:
	python tools/serve_loadtest.py --fleet 10 --requests 400000 \
	  --client-procs 12 --concurrency 5 --mutations 6 --sse 4 \
	  --feed-poll 0.5

# Crash flight-recorder check (docs/OBSERVABILITY.md "Flight recorder"):
# a subprocess run SIGTERM'd mid-batch must die with real SIGTERM
# semantics AND leave a parseable postmortem.json (per-thread event
# rings, breaker/quarantine state, config fingerprint), and `--resume`
# must recover the store row-for-row identical to an uninterrupted run.
postmortem-smoke:
	python tools/postmortem_smoke.py

# Active-lane compaction check (docs/ROOFLINE.md "Occupancy"): the same
# synthetic tile with compaction on vs off — asserts the stores are
# byte-identical, the loop actually compacted (kernel_compactions > 0),
# and wasted lane-rounds dropped at least 2x; artifact folded by bench.py.
compact-smoke:
	python tools/compact_smoke.py

# Wire-diet regression probe (docs/ROOFLINE.md "Wire budget"): one
# staged batch on CPU — asserts every staged ingress plane is integer
# (no float h2d), the egress tables are int-coded and decode bit-exactly,
# and the packed drain is measurably smaller than the raw f32 fetch;
# artifact folded by bench.py.
wire-smoke:
	python tools/wire_probe.py

# Fused-fit / rebalancing-ring check (docs/ROOFLINE.md "Fused fit"):
# fused on/off dispatches byte-identical, occupancy counters still
# moving, and the straggler ring migrating lanes row-identically on a
# forced-ragged 2-device simulated mesh; artifact folded by bench.py.
fuse-smoke:
	python tools/fuse_smoke.py

# Mosaic SIGABRT bisection (the r05 mega/fused-combo compiler crash):
# compiles each multi-phase pairing in subprocesses across a lane-block
# ladder and records the smallest failing shape as a classified,
# bench-foldable artifact.  CPU hosts record the honest
# interpret-only caveat.
fuse-repro:
	python tools/fuse_repro.py

# Mixed-precision envelope check (docs/ROOFLINE.md "Precision"): mixed
# vs f32 dispatches decision-identical (break days/QA/segment counts/
# curve ranks), coef/rmse drift inside the pinned scaled-ulp budget,
# and the mixed trace counter moving; artifact folded by bench.py.
precision-smoke:
	python tools/precision_smoke.py

# Alerting end-to-end drill (docs/ALERTS.md): a streaming run over a
# step-change archive with injected ingest faults and a SIGKILL
# mid-stream — asserts zero lost alerts, zero duplicates after the
# resume, webhook delivery catching up from its durable cursor, repair
# jobs enqueued once per broken chip and drained by a fleet worker, and
# an evaluated acquisition→alert-visible freshness SLO in the artifact
# (folded by bench.py).
alert-smoke:
	python tools/alert_soak.py

# Streaming-first end-to-end drill (docs/STREAMING.md): a standing
# fleet — `firebird watch` + N `fleet work --forever` workers — drains
# synthetic scenes as they land on the manifest, with the watcher AND
# one worker SIGKILLed mid-drain; asserts every scene processed exactly
# once across watcher incarnations, every alert delivered exactly once,
# the packed tile statestore byte-identical to a clean serial leg, and
# the acquisition→alert freshness SLO evaluated over real observations
# (artifact folded by bench.py next to the e2e block).
streamfleet-smoke:
	python tools/stream_fleet_soak.py

# Fleet telemetry-plane drill (docs/OBSERVABILITY.md "Fleet telemetry
# plane"): a standing watcher + 2-worker fleet over a landing zone, the
# worker holding the alerting job SIGKILLed mid-lease, a separate
# deliverer process pushing the webhook backlog — then `firebird trace
# collect` must merge every process's spool (including the SIGKILLed
# one's recovered segments) into ONE Perfetto trace where the alerting
# scene's trace id crosses >=4 OS processes, with a per-alert
# critical-path breakdown summing to the measured
# acquisition_to_alert_seconds within 10%; a FIREBIRD_TELEMETRY=0 leg
# proves disarmed telemetry writes nothing (artifact folded by bench.py).
telemetry-smoke:
	python tools/telemetry_smoke.py

# Object-tier chaos drill (docs/ROBUSTNESS.md "Object tier"): the
# chunked conditional-put protocol, 3-way store parity (plain sqlite /
# env-armed mirror / pure object backend row-identical), stale object
# fences rejected 100% with a durable census, torn uploads (truncated
# chunk, dropped manifest) recovered by generation fallback, a SIGKILL
# between chunk upload and manifest commit leaving no visible partial
# object, and the orphan scrubber reclaiming the debris; statestore and
# pyramid object legs ride along (artifact folded by bench.py).
objectstore-smoke:
	python tools/objectstore_chaos.py

# Fanout-plane drill (docs/ALERTS.md "Fanout plane"): quadkey-sharded
# subscription index + fleet-powered delivery at a scaled-down tier —
# audience resolution must stay flat across subscriber milestones
# (index vs brute-force scan), a 10k-pair burst must land exactly-once
# through a fanout worker SIGKILLed mid-drain (0 duplicate re-POSTs by
# record id), digest/batch policies must flush, and shard-job
# completion p99 must beat the fanout_p99 budget leg; artifact folded
# by bench.py.  `fanout-proof` is the full 1M-subscriber / 10k-alert
# headline run (several minutes — not part of `make test`).
fanout-smoke:
	python tools/fanout_loadtest.py --subscribers 50000 --alerts 2000 \
	  --workers 3

fanout-proof:
	python tools/fanout_loadtest.py

# Error-budget plane drill (docs/OBSERVABILITY.md "Error budgets"):
# fleet + black-box canary prober; injected serve brownout + watcher
# stall must trip the multi-window burn verdict durably, and metric
# history must survive a SIGKILLed serving process + a prober restart.
slo-smoke:
	python tools/slo_smoke.py

image:
	docker build -f deploy/Dockerfile -t firebird .

# ---- results store (reference Makefile:24-39 docker-up + db-schema) ----

db-up:
	$(COMPOSE) up -d --wait cassandra

# Apply the generated DDL (`firebird schema`) through the container's
# cqlsh — the reference pipes resources/schema.cql the same way.
db-schema:
	firebird schema | $(COMPOSE) exec -T cassandra cqlsh

# Gated live round-trip test against the composed Cassandra (skips
# cleanly when the service is unreachable).
db-test:
	CASSANDRA=127.0.0.1 CASSANDRA_PORT=9043 \
	python -m pytest tests/test_cassandra_live.py -v

db-down:
	$(COMPOSE) down

# ---- run aliases (ccdc.install.example:86-94) ----

changedetection:
	firebird changedetection -x $(X) -y $(Y) -a $(ACQUIRED)

classification:
	firebird classification -x $(X) -y $(Y) -s 724204 -e 735598

clean:
	rm -rf build dist *.egg-info .pytest_cache
	find . -name __pycache__ -prune -exec rm -rf {} +
