"""Quadkey tile pyramid + changefeed coherence + edge caching
(firebird_tpu.serve.pyramid / serve.changefeed; docs/SERVING.md)."""

import json
import os
import random
import urllib.error
import urllib.request

import numpy as np
import pytest

from firebird_tpu import grid, products
from firebird_tpu.ccd.params import FILL_VALUE
from firebird_tpu.config import Config
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.serve import api as serve_api
from firebird_tpu.serve import pyramid as pyr
from firebird_tpu.serve.cache import StoreGenerations
from firebird_tpu.serve.changefeed import (ChangefeedConsumer,
                                           ProductWrites)
from firebird_tpu.store import open_store
from firebird_tpu.utils import dates as dt

CX, CY = (int(v) for v in grid.snap(100, 200)["chip"]["proj-pt"])
DATE = "1996-01-01"
CHIP_M = 3000


@pytest.fixture
def fresh_metrics():
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


def seg_frame(cx=CX, cy=CY, curqa=(4, 8, 4), n=3):
    return {
        "cx": [cx] * n, "cy": [cy] * n,
        "px": [cx + 30 * i for i in range(n)],
        "py": [cy - 30] * n,
        "sday": ["1995-01-01"] * n, "eday": ["1999-01-01"] * n,
        "bday": ["1997-06-01"] * n, "chprob": [1.0] * n,
        "curqa": list(curqa)[:n],
        "rfrawp": [None] * n,
    }


def seeded_store(chips=((CX, CY),)):
    store = open_store("memory", "", "t")
    for cx, cy in chips:
        store.write("segment", seg_frame(cx, cy))
    return store


# ---------------------------------------------------------------------------
# Quadkey / Albers math
# ---------------------------------------------------------------------------

def test_quadkey_round_trip_every_zoom():
    rng = random.Random(7)
    for z in range(pyr.Z_BASE + 1):
        for _ in range(8):
            x = rng.randrange(1 << z)
            y = rng.randrange(1 << z)
            qk = pyr.quadkey(z, x, y)
            assert len(qk) == z
            assert pyr.tile_from_quadkey(qk) == (z, x, y)
    assert pyr.tile_from_quadkey("") == (0, 0, 0)
    with pytest.raises(ValueError):
        pyr.tile_from_quadkey("4")
    with pytest.raises(ValueError):
        pyr.quadkey(2, 4, 0)               # x outside the level domain


def test_albers_round_trip_every_zoom():
    """quadkey<->Albers: a tile's UL projection corner must map back to
    the same tile at every zoom level (the satellite property test)."""
    rng = random.Random(13)
    for z in range(pyr.Z_BASE + 1):
        for _ in range(8):
            x = rng.randrange(1 << z)
            y = rng.randrange(1 << z)
            ext = pyr.tile_extent(z, x, y)
            # UL corner and an interior point both land in the tile.
            assert pyr.tile_for_point(ext["ulx"], ext["uly"], z) == (x, y)
            assert pyr.tile_for_point(
                ext["ulx"] + 1.0, ext["uly"] - 1.0, z) == (x, y)
            # extent is chip-grid aligned and the right size
            span = 1 << (pyr.Z_BASE - z)
            assert ext["lrx"] - ext["ulx"] == span * CHIP_M
            assert ext["uly"] - ext["lry"] == span * CHIP_M


def test_tile_chip_mapping_and_tree():
    bx, by = pyr.tile_of_chip(CX, CY)
    assert pyr.chips_of_tile(pyr.Z_BASE, bx, by) == [(CX, CY)]
    z, x, y = pyr.parent(pyr.Z_BASE, bx, by)
    assert (bx >> 1, by >> 1) == (x, y)
    kids = pyr.children(z, x, y)
    assert (pyr.Z_BASE, bx, by) in kids and len(kids) == 4
    anc = pyr.ancestors(pyr.Z_BASE, bx, by)
    assert len(anc) == pyr.Z_BASE + 1 and anc[-1][0] == 0
    # every chip of the parent tile maps back to it
    for cx, cy in pyr.chips_of_tile(z, x, y):
        assert pyr.tile_of_chip(cx, cy, z) == (x, y)
    # off-domain chips reject with the quadkey-domain message
    with pytest.raises(ValueError, match="quadkey domain"):
        pyr.tile_of_chip(-3_000_000.0, CY)


def test_downsample2x_is_selection():
    cells = np.arange(16, dtype=np.int32).reshape(4, 4)
    got = pyr.downsample2x(cells)
    assert got.tolist() == [[0, 2], [8, 10]]


# ---------------------------------------------------------------------------
# TilePyramid: build, versioning, invalidation
# ---------------------------------------------------------------------------

def test_base_tile_byte_identical_to_products(tmp_path, fresh_metrics):
    store = seeded_store()
    p = pyr.TilePyramid(str(tmp_path), pyr.store_read_chip(store))
    bx, by = pyr.tile_of_chip(CX, CY)
    cells, meta = p.tile("curveqa", DATE, pyr.Z_BASE, bx, by)
    want = products.chip_product("curveqa", dt.to_ordinal(DATE), CX, CY,
                                 store.read("segment",
                                            {"cx": CX, "cy": CY}))
    assert np.array_equal(cells.ravel(), want)
    assert cells.dtype == np.int32
    assert meta["version"] == 1 and not meta["stale"]
    assert meta["quadkey"] == pyr.quadkey(pyr.Z_BASE, bx, by)
    # compute-on-miss persisted the product row (store_read_chip shares
    # the products.save path)
    rows = store.read("product", {"name": "curveqa", "date": DATE,
                                  "cx": CX, "cy": CY})
    assert rows["cells"]
    # the persisted file serves the repeat without a rebuild
    built = obs_metrics.counter("pyramid_tiles_built").value
    cells2, meta2 = p.tile("curveqa", DATE, pyr.Z_BASE, bx, by)
    assert meta2["version"] == 1
    assert obs_metrics.counter("pyramid_tiles_built").value == built
    assert obs_metrics.counter("pyramid_tile_hits").value >= 1


def test_parent_downsamples_children(tmp_path, fresh_metrics):
    store = seeded_store()
    p = pyr.TilePyramid(str(tmp_path), pyr.store_read_chip(store))
    bx, by = pyr.tile_of_chip(CX, CY)
    base, _ = p.tile("curveqa", DATE, pyr.Z_BASE, bx, by)
    z, x, y = pyr.parent(pyr.Z_BASE, bx, by)
    cells, meta = p.tile("curveqa", DATE, z, x, y)
    assert cells.shape == (pyr.TILE_SIDE, pyr.TILE_SIDE)
    half = pyr.TILE_SIDE // 2
    dx, dy = bx - 2 * x, by - 2 * y
    quadrant = cells[dy * half:(dy + 1) * half, dx * half:(dx + 1) * half]
    assert np.array_equal(quadrant, pyr.downsample2x(base))
    # sibling quadrants cover chips with no data: FILL, and the empty
    # base tiles persisted as negative cache
    other = cells[(1 - dy) * half:(2 - dy) * half,
                  dx * half:(dx + 1) * half]
    assert (other == FILL_VALUE).all()


def test_invalidation_is_surgical_and_versions_rise(tmp_path,
                                                    fresh_metrics):
    chips = [(CX, CY), (CX + CHIP_M, CY)]
    store = seeded_store(chips)
    p = pyr.TilePyramid(str(tmp_path), pyr.store_read_chip(store))
    t0 = pyr.tile_of_chip(*chips[0])
    t1 = pyr.tile_of_chip(*chips[1])
    assert t0 != t1
    for bx, by in (t0, t1):
        p.tile("curveqa", DATE, pyr.Z_BASE, bx, by)
    n = p.invalidate_chip(*chips[0])
    assert n >= 1
    assert p.peek_meta("curveqa", DATE, pyr.Z_BASE, *t0)["stale"]
    assert not p.peek_meta("curveqa", DATE, pyr.Z_BASE, *t1)["stale"]
    # rebuild bumps the version (ETags can never collide with the
    # stale tile's), and a second invalidation of an already-stale
    # tile is a no-op
    _, meta = p.tile("curveqa", DATE, pyr.Z_BASE, *t0)
    assert meta["version"] == 2 and not meta["stale"]
    assert obs_metrics.counter("pyramid_tiles_dirtied").value == n
    # off-domain chips dirty nothing (and do not raise)
    assert p.invalidate_chip(-3_000_000.0, CY) == 0


def test_compute_on_miss_depth_floor(tmp_path):
    store = seeded_store()
    p = pyr.TilePyramid(str(tmp_path), pyr.store_read_chip(store))
    with pytest.raises(LookupError, match="not\\s+precomputed"):
        p.tile("curveqa", DATE, 0, 0, 0)
    # within the floor, misses build
    bx, by = pyr.tile_of_chip(CX, CY, pyr.Z_BASE - pyr.MAX_MISS_DEPTH)
    cells, _ = p.tile("curveqa", DATE,
                      pyr.Z_BASE - pyr.MAX_MISS_DEPTH, bx, by)
    assert (cells != FILL_VALUE).any()


def test_build_area_two_levels(tmp_path):
    chips = [(CX + CHIP_M * i, CY - CHIP_M * j)
             for i in range(2) for j in range(2)]
    store = seeded_store(chips)
    p = pyr.TilePyramid(str(tmp_path), pyr.store_read_chip(store))
    bounds = [(CX + 1.0, CY - 1.0),
              (CX + 2 * CHIP_M - 1.0, CY - 2 * CHIP_M + 1.0)]
    summary = p.build_area(["curveqa"], [DATE], bounds, levels=2)
    assert summary["chips"] == 4
    assert summary["levels"][str(pyr.Z_BASE)]["built"] == 4
    assert summary["levels"][str(pyr.Z_BASE - 1)]["built"] >= 1
    # second build skips everything (fresh)
    again = p.build_area(["curveqa"], [DATE], bounds, levels=2)
    assert again["levels"][str(pyr.Z_BASE)]["built"] == 0
    st = p.status()
    assert st["tiles_by_level"][str(pyr.Z_BASE)]["tiles"] >= 4
    # bounds off the quadkey domain reject with the domain message
    with pytest.raises(ValueError, match="quadkey domain"):
        p.build_area(["curveqa"], [DATE],
                     [(-3_000_000.0, CY)], levels=1)


# ---------------------------------------------------------------------------
# Changefeed: product_writes feed, consumer, replica registry
# ---------------------------------------------------------------------------

def test_product_writes_feed_cursors(tmp_path):
    feed = ProductWrites(str(tmp_path / "cf.db"))
    try:
        assert feed.latest_cursor() == 0
        assert feed.append("product", [(CX, CY), (CX + CHIP_M, CY)]) == 2
        recs = feed.since(0)
        assert [r["id"] for r in recs] == [1, 2]
        assert recs[0]["table"] == "product"
        assert feed.since(2) == []
        # checkpoint is monotonic forward: stale state cannot rewind
        feed.checkpoint("r1", alert_cursor=5, writes_cursor=2)
        feed.checkpoint("r1", alert_cursor=3, writes_cursor=1)
        assert feed.replica_cursors("r1") == (5, 2)
        assert feed.replica_cursors("unknown") == (0, 0)
        reps = feed.replicas()
        assert len(reps) == 1 and reps[0]["writes_behind"] == 0
    finally:
        feed.close()


def test_consumer_applies_and_resumes(tmp_path, fresh_metrics):
    feed = ProductWrites(str(tmp_path / "cf.db"))
    gens = StoreGenerations()
    store = seeded_store()
    p = pyr.TilePyramid(str(tmp_path / "pyr"),
                        pyr.store_read_chip(store))
    bx, by = pyr.tile_of_chip(CX, CY)
    p.tile("curveqa", DATE, pyr.Z_BASE, bx, by)
    try:
        cons = ChangefeedConsumer(gens, feed=feed, pyramid=p,
                                  replica="r1", poll_sec=60)
        feed.append("product", [(CX, CY)])
        out = cons.poll_once()
        assert out["applied"] == 1 and out["writes_cursor"] == 1
        assert gens.gen("product", CX, CY) == 1
        assert p.peek_meta("curveqa", DATE, pyr.Z_BASE, bx, by)["stale"]
        assert obs_metrics.counter(
            "changefeed_records_applied").value == 1
        # lag gauge exists (0 <= lag, caught-up polls read 0)
        assert obs_metrics.gauge(
            "serve_changefeed_lag_seconds").value >= 0
        # a NEW consumer with the same replica id resumes from the
        # durable cursor: nothing re-applies
        cons2 = ChangefeedConsumer(gens, feed=feed, pyramid=p,
                                   replica="r1", poll_sec=60)
        assert cons2.poll_once()["applied"] == 0
        # an UNSEEN replica id replays the whole feed (the safe
        # default for an unknown cache dir)
        cons3 = ChangefeedConsumer(StoreGenerations(), feed=feed,
                                   replica="r2", poll_sec=60)
        assert cons3.poll_once()["applied"] == 1
        assert len(feed.replicas()) == 2
    finally:
        feed.close()


def test_consumer_tails_alert_log(tmp_path, fresh_metrics):
    from firebird_tpu.alerts.log import AlertLog

    alog = AlertLog(str(tmp_path / "alerts.db"))
    gens = StoreGenerations()
    try:
        alog.append([{"cx": CX, "cy": CY, "px": CX, "py": CY - 30,
                      "break_day": 728000}])
        cons = ChangefeedConsumer(gens, alerts=alog, replica="r1",
                                  poll_sec=60)
        out = cons.poll_once()
        assert out["applied"] == 1 and out["alert_cursor"] == 1
        # an alert is a segment-rows republish: the segment generation
        # (which every cached frame/raster key embeds) bumps
        assert gens.gen("segment", CX, CY) == 1
        assert cons.poll_once()["applied"] == 0
    finally:
        alog.close()


def test_gens_on_bump_hook_fires_outside_lock():
    seen = []
    gens = StoreGenerations(on_bump=lambda t, cx, cy:
                            seen.append((t, cx, cy)))
    gens.bump("segment", CX, CY)
    gens.bump_frame("product", {"cx": [CX], "cy": [CY]})
    assert seen == [("segment", CX, CY), ("product", CX, CY)]


# ---------------------------------------------------------------------------
# HTTP: /v1/pyramid + ETag/304 edge contract
# ---------------------------------------------------------------------------

def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    try:
        r = urllib.request.urlopen(req, timeout=10)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.fixture
def served_pyramid(tmp_path, fresh_metrics):
    store = seeded_store()
    p = pyr.TilePyramid(str(tmp_path / "pyr"))
    svc = serve_api.ServeService(store, Config(store_backend="memory"),
                                 pyramid=p)
    srv = serve_api.start_serve_server(0, svc, host="127.0.0.1")
    yield svc, store, f"http://127.0.0.1:{srv.port}"
    srv.close()


def test_http_pyramid_tile_and_304(served_pyramid):
    svc, store, base = served_pyramid
    bx, by = pyr.tile_of_chip(CX, CY)
    path = f"/v1/pyramid/curveqa/{pyr.Z_BASE}/{bx}/{by}?date={DATE}"
    code, body, h = _get(base, path)
    assert code == 200
    import io
    arr = np.load(io.BytesIO(body))
    want = products.chip_product("curveqa", dt.to_ordinal(DATE), CX, CY,
                                 store.read("segment",
                                            {"cx": CX, "cy": CY}))
    assert np.array_equal(arr.ravel(), want)
    assert h["X-Firebird-Quadkey"] == pyr.quadkey(pyr.Z_BASE, bx, by)
    etag = h["ETag"]
    assert etag.startswith('"') and "max-age=" in h["Cache-Control"]
    # revalidation: 304, empty body, counted
    code, body, h2 = _get(base, path, {"If-None-Match": etag})
    assert (code, body) == (304, b"")
    assert h2["ETag"] == etag
    assert obs_metrics.counter("serve_304_total").value == 1
    # json format carries the addressing + extent
    code, body, _ = _get(base, path + "&format=json")
    doc = json.loads(body)
    assert (doc["z"], doc["x"], doc["y"]) == (pyr.Z_BASE, bx, by)
    assert doc["version"] == 1 and doc["extent"]["chip_span"] == 1


def test_http_pyramid_errors(served_pyramid):
    svc, _, base = served_pyramid
    code, body, _ = _get(base, f"/v1/pyramid/curveqa/3/1?date={DATE}")
    assert code == 400 and b"/v1/pyramid/<name>/<z>/<x>/<y>" in body
    code, body, _ = _get(base, f"/v1/pyramid/nope/3/1/1?date={DATE}")
    assert code == 400
    code, body, _ = _get(base,
                         f"/v1/pyramid/curveqa/3/999/0?date={DATE}")
    assert code == 400 and b"domain" in body
    code, body, _ = _get(base, f"/v1/pyramid/curveqa/0/0/0?date={DATE}")
    assert code == 404 and b"precomputed" in body
    # no pyramid mounted -> 404 with guidance
    svc.pyramid = None
    code, body, _ = _get(base,
                         f"/v1/pyramid/curveqa/11/1/1?date={DATE}")
    assert code == 404 and b"pyramid root" in body


def test_http_product_etag_flips_on_write(served_pyramid):
    """The edge contract on /v1/product: ETag + 304, and a write
    through the watched store flips the revalidation to a fresh 200
    with a new tag (in-process coherence; the changefeed provides the
    same flip cross-process)."""
    svc, store, base = served_pyramid
    path = f"/v1/product/curveqa?cx={CX}&cy={CY}&date={DATE}"
    code, _, h = _get(base, path)
    assert code == 200
    etag = h["ETag"]
    code, body, _ = _get(base, path, {"If-None-Match": etag})
    assert (code, body) == (304, b"")
    svc.watched_store().write("segment", seg_frame(curqa=(9, 9, 9)))
    code, _, h2 = _get(base, path, {"If-None-Match": etag})
    assert code == 200 and h2["ETag"] != etag
    # the in-process bump also dirtied the pyramid (gens.on_bump hook)
    bx, by = pyr.tile_of_chip(CX, CY)
    svc.pyramid.tile("curveqa", DATE, pyr.Z_BASE, bx, by)
    svc.watched_store().write("segment", seg_frame(curqa=(5, 5, 5)))
    assert svc.pyramid.peek_meta("curveqa", DATE, pyr.Z_BASE,
                                 bx, by)["stale"]


def test_http_tile_etag_covers_all_chips(served_pyramid):
    svc, store, base = served_pyramid
    path = (f"/v1/tile/curveqa?bounds={CX + 1},{CY - 1}"
            f"&bounds={CX + CHIP_M + 1},{CY - 1}&date={DATE}")
    code, _, h = _get(base, path)
    assert code == 200
    etag = h["ETag"]
    code, body, _ = _get(base, path, {"If-None-Match": etag})
    assert (code, body) == (304, b"")
    # writing the SECOND chip (not the first) still flips the mosaic
    svc.watched_store().write("segment", seg_frame(cx=CX + CHIP_M))
    code, _, h2 = _get(base, path, {"If-None-Match": etag})
    assert code == 200 and h2["ETag"] != etag


# ---------------------------------------------------------------------------
# Fleet pyramid job
# ---------------------------------------------------------------------------

def test_fleet_pyramid_job_builds_area(tmp_path, fresh_metrics):
    """A `pyramid` job on the fleet queue materializes the payload's
    area through the real worker handler (fenced store; idempotent
    atomic tile writes)."""
    from firebird_tpu.fleet.queue import FleetQueue
    from firebird_tpu.fleet.worker import FleetWorker

    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"),
                 serve_pyramid_dir=str(tmp_path / "pyr"))
    store = open_store("sqlite", cfg.store_path, cfg.keyspace())
    store.write("segment", seg_frame())
    store.close()
    q = FleetQueue(str(tmp_path / "fleet.db"))
    try:
        q.enqueue("pyramid", {
            "bounds": [[CX + 1.0, CY - 1.0]],
            "products": ["curveqa"], "product_dates": [DATE],
            "levels": 2})
        summary = FleetWorker(cfg, q).run()
        assert summary["acked"] == 1 and summary["dead"] == 0
    finally:
        q.close()
    p = pyr.TilePyramid(str(tmp_path / "pyr"))
    bx, by = pyr.tile_of_chip(CX, CY)
    meta = p.peek_meta("curveqa", DATE, pyr.Z_BASE, bx, by)
    assert meta is not None and meta["version"] == 1
    assert p.peek_meta("curveqa", DATE,
                       *pyr.parent(pyr.Z_BASE, bx, by)) is not None
