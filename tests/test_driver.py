"""End-to-end driver + CLI tests: the minimum slice of SURVEY.md §7 —
synthetic source -> packer -> CCD kernel -> format -> store -> CLI."""

import numpy as np
import pytest
from click.testing import CliRunner

from firebird_tpu import cli, grid
from firebird_tpu.config import Config
from firebird_tpu.driver import core
from firebird_tpu.ingest import SyntheticSource
from firebird_tpu.store import MemoryStore

ACQ = "1995-01-01/1997-06-01"  # short archive so CPU compile stays fast
# chips_per_batch=1 keeps every kernel dispatch on the same [1,7,P,T]
# compiled shape, so all tests in this module share one jit cache entry;
# device_sharding='off' keeps full-chip dispatches from padding 1 -> 8
# virtual devices (the sharded driver path is covered on sliced batches by
# test_detect_batch_shards_and_pads).
CFG = Config(store_backend="memory", source_backend="synthetic",
             chips_per_batch=1, dtype="float64", device_sharding="off",
             fetch_retries=0)


@pytest.fixture(scope="module")
def run_result():
    store = MemoryStore("test")
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    done = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                                chunk_size=2, cfg=CFG, source=src,
                                store=store)
    return done, store


def test_changedetection_end_to_end(run_result):
    done, store = run_result
    assert len(done) == 2
    # chip table: one row per chip with the aligned ISO dates
    chips = store.read("chip")
    assert len(chips["cx"]) == 2
    assert all(d.startswith("1995-") for d in chips["dates"][0][:1])
    # pixel table: 10k masks per chip
    assert store.count("pixel") == 20000
    # segment table: at least one row per pixel (sentinel or real)
    assert store.count("segment") >= 20000
    seg = store.read("segment", {"cx": done[0][0], "cy": done[0][1]})
    assert len(seg["cx"]) >= 10000
    # real segments carry models
    real = [i for i, s in enumerate(seg["sday"]) if s != "0001-01-01"]
    assert len(real) >= 9000
    i = real[0]
    assert seg["nicoef"][i] is not None and len(seg["nicoef"][i]) == 7
    assert seg["nirmse"][i] > 0


def test_rerun_is_idempotent(run_result):
    done, store = run_result
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    before = store.count("segment")
    core.changedetection(x=100, y=200, acquired=ACQ, number=1, chunk_size=1,
                         cfg=CFG, source=src, store=store)
    assert store.count("segment") == before


def test_float64_config_enables_x64():
    """FIREBIRD_DTYPE=float64 must actually compute in f64 — without
    jax_enable_x64, jnp silently downcasts and a 'bit-parity run' would
    run at single precision."""
    import jax

    assert jax.config.jax_enable_x64      # conftest baseline
    try:
        jax.config.update("jax_enable_x64", False)
        store = MemoryStore("x64test")
        src = SyntheticSource(seed=9, start="1995-01-01", end="1996-06-01")
        core.changedetection(x=100, y=200, acquired="1995-01-01/1996-06-01",
                             number=1, chunk_size=1, cfg=CFG, source=src,
                             store=store)
        assert jax.config.jax_enable_x64  # detect_chunk turned it back on
        # and the store actually holds results (the run happened)
        assert store.count("segment") >= 10000
    finally:
        jax.config.update("jax_enable_x64", True)


def test_host_shard_partitions_without_overlap(monkeypatch):
    """Multi-host runs split the chip list disjointly and completely —
    the union of all hosts' work equals the single-host run."""
    import jax

    cids = [(i, 0) for i in range(10)]
    assert core.host_shard(cids) == cids      # single-process: unchanged

    shards = []
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    for i in range(3):
        monkeypatch.setattr(jax, "process_index", lambda i=i: i)
        shards.append(core.host_shard(cids))
    flat = [c for s in shards for c in s]
    assert sorted(flat) == cids               # complete, no overlap
    assert max(len(s) for s in shards) - min(len(s) for s in shards) <= 1


def test_chunk_failure_isolation():
    """A source that explodes on one chunk must not kill the run
    (core.py:115-124 semantics)."""
    store = MemoryStore("test")
    good = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01")
    calls = {"n": 0}

    class Flaky:
        def chip(self, cx, cy, acquired=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise IOError("chipmunk down")
            return good.chip(cx, cy, acquired)

    done = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                                chunk_size=1, cfg=CFG, source=Flaky(),
                                store=store)
    assert len(done) == 1           # first chunk failed, second landed
    assert store.count("chip") == 1


def test_resume_skips_stored_chips(run_result):
    done, store = run_result

    class Explodes:
        def chip(self, cx, cy, acquired=None):
            raise AssertionError("resume must not refetch stored chips")

    out = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                               chunk_size=2, cfg=CFG, source=Explodes(),
                               store=store, resume=True)
    assert set(out) == set(done)    # all skipped, none refetched


def test_transient_fetch_retries(monkeypatch):
    """A transient per-chip fetch failure is absorbed by the retry loop
    instead of failing the chunk (Spark-task-retry semantics)."""
    monkeypatch.setattr(core.time, "sleep", lambda s: None)
    store = MemoryStore("test")
    good = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01")
    calls = {"n": 0}

    class Transient:
        def chip(self, cx, cy, acquired=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise IOError("blip")
            return good.chip(cx, cy, acquired)

    cfg = Config(store_backend="memory", source_backend="synthetic",
                 chips_per_batch=1, dtype="float64", device_sharding="off",
                 fetch_retries=2)
    done = core.changedetection(x=100, y=200, acquired=ACQ, number=1,
                                chunk_size=1, cfg=cfg, source=Transient(),
                                store=store)
    assert len(done) == 1 and calls["n"] == 2
    assert store.count("chip") == 1


def test_detect_batch_shards_and_pads():
    """detect_batch pads a 3-chip batch over the 8 virtual devices and
    matches the single-device result (pixel-sliced to stay quick)."""
    import jax

    from firebird_tpu.ccd import kernel
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    assert jax.local_device_count() == 8
    src = SyntheticSource(seed=3, start="1995-01-01", end="1997-01-01")
    p = pack([src.chip(100 + 3000 * i, 200) for i in range(3)], bucket=32)
    small = PackedChips(cids=p.cids, dates=p.dates,
                        spectra=p.spectra[:, :, :64, :],
                        qas=p.qas[:, :64, :], n_obs=p.n_obs)
    import jax.numpy as jnp
    seg, n_real = core.detect_batch(small, jnp.float64, "auto")
    assert n_real == 3
    assert seg.n_segments.shape[0] == 8      # padded over the mesh
    ref = kernel.detect_packed(small, dtype=jnp.float64)
    for f in ("n_segments", "seg_meta", "mask", "procedure"):
        np.testing.assert_array_equal(
            np.asarray(getattr(seg, f))[:3], np.asarray(getattr(ref, f)))


def test_pad_batch_noop_and_repeat():
    from firebird_tpu.ingest import SyntheticSource, pack

    src = SyntheticSource(seed=3, start="1995-01-01", end="1996-01-01")
    p = pack([src.chip(100, 200)], bucket=32)
    same, n = core._pad_batch(p, 1)
    assert same is p and n == 1
    padded, n = core._pad_batch(p, 4)
    assert n == 1 and padded.n_chips == 4
    np.testing.assert_array_equal(padded.spectra[3], p.spectra[0])


def test_drain_recomputes_on_capacity_overflow():
    """The driver dispatches without the capacity check (to stay
    asynchronous); the drain thread must detect an overflowed result and
    recompute before persisting — all segments land in the store."""
    import jax.numpy as jnp

    from firebird_tpu.ccd import kernel
    from firebird_tpu.obs import Counters
    from firebird_tpu.store import AsyncWriter
    from test_ccd_kernel import overflow_packed

    p = overflow_packed()
    seg = kernel.detect_packed(p, dtype=jnp.float64, check_capacity=False)
    worst = int(np.asarray(seg.n_segments).max())
    assert worst > kernel.MAX_SEGMENTS     # raw result really overflows
    store = MemoryStore("overflow")
    writer = AsyncWriter(store)
    try:
        core.drain_batch(seg, p, 1, writer=writer, counters=Counters(),
                         dtype=jnp.float64)
        writer.flush()
    finally:
        writer.close()
    rows = store.read("segment", {"px": 0, "py": 0})
    real = [s for s in rows["sday"] if s != "0001-01-01"]
    assert len(real) == worst              # every closed segment persisted


def test_cli_status_reports_store_and_tile_progress(tmp_path, monkeypatch):
    from firebird_tpu.store import SqliteStore

    db = str(tmp_path / "fb.db")
    monkeypatch.setenv("FIREBIRD_STORE_BACKEND", "sqlite")
    monkeypatch.setenv("FIREBIRD_STORE_PATH", db)
    store = SqliteStore(db, Config.from_env().keyspace())
    tile = grid.tile(542000, 1650000)
    cx, cy = (int(v) for v in tile["chips"][0])
    store.write("segment", {
        "cx": [cx], "cy": [cy], "px": [cx], "py": [cy],
        "sday": ["2000-01-01"], "eday": ["2005-01-01"],
        "bday": ["2005-01-01"], "chprob": [1.0], "curqa": [8]})
    res = CliRunner().invoke(cli.entrypoint, [
        "status", "-x", "542000", "-y", "1650000"])
    assert res.exit_code == 0, res.output
    import json

    rep = json.loads(res.output)
    assert rep["backend"] == "sqlite"
    assert rep["tables"]["segment"] == 1
    assert rep["chips_with_segments"] == 1
    assert rep["tile"] == {"h": 20, "v": 11, "chips_done": 1,
                           "chips_total": 2500}
    # one coordinate without the other is a usage error
    res = CliRunner().invoke(cli.entrypoint, ["status", "-x", "542000"])
    assert res.exit_code != 0


def test_fetch_mirrors_tile_to_file_source(tmp_path):
    """fetch writes a FileSource archive that reproduces the live source:
    same chip payloads, usable by a subsequent file-sourced run."""
    import numpy as np

    from firebird_tpu.driver import core
    from firebird_tpu.ingest import FileSource, SyntheticSource

    src = SyntheticSource(seed=2, start="1995-01-01", end="1996-06-01")
    cfg = Config(source_backend="synthetic", store_backend="memory")
    n, attempted = core.fetch(x=542000, y=1650000, outdir=str(tmp_path),
                              number=3, aux=True, cfg=cfg, source=src,
                              aux_source=src)
    assert (n, attempted) == (3, 3)
    files = sorted(p.name for p in tmp_path.iterdir())
    assert len([f for f in files if f.startswith("chip_")]) == 3
    assert len([f for f in files if f.startswith("aux_")]) == 3
    # round-trip equality against the live source for one chip
    cx, cy = (int(v) for v in grid.tile(542000, 1650000)["chips"][0])
    live = src.chip(cx, cy, "1995-01-01/1996-06-01")
    mirrored = FileSource(str(tmp_path)).chip(cx, cy,
                                              "1995-01-01/1996-06-01")
    np.testing.assert_array_equal(live.spectra, mirrored.spectra)
    np.testing.assert_array_equal(live.qas, mirrored.qas)
    np.testing.assert_array_equal(live.dates, mirrored.dates)
    aux = FileSource(str(tmp_path)).aux(cx, cy)
    assert set(aux) == {"dem", "trends", "aspect", "posidex", "slope",
                        "mpw"}


def test_cli_changedetection(monkeypatch, tmp_path):
    monkeypatch.setenv("FIREBIRD_SOURCE", "synthetic")
    monkeypatch.setenv("FIREBIRD_STORE_BACKEND", "sqlite")
    monkeypatch.setenv("FIREBIRD_STORE_PATH", str(tmp_path / "fb.db"))
    monkeypatch.setenv("FIREBIRD_DTYPE", "float64")
    monkeypatch.setenv("FIREBIRD_DEVICE_SHARDING", "off")
    res = CliRunner().invoke(
        cli.entrypoint,
        ["changedetection", "-x", "100", "-y", "200", "-n", "1",
         "-a", ACQ, "-c", "1"])
    assert res.exit_code == 0, res.output

    from firebird_tpu.store import SqliteStore
    ks = Config.from_env().keyspace()
    store = SqliteStore(str(tmp_path / "fb.db"), ks)
    assert store.count("chip") == 1
    assert store.count("segment") >= 10000


def test_driver_source_factory():
    assert isinstance(core.make_source(Config(source_backend="synthetic")),
                      SyntheticSource)
    from firebird_tpu.ingest import ChipmunkSource
    assert isinstance(core.make_source(Config(source_backend="chipmunk")),
                      ChipmunkSource)
    with pytest.raises(ValueError):
        core.make_source(Config(source_backend="nope"))


def test_cli_tiles_csv_and_sharding():
    runner = CliRunner()
    args = ["tiles", "-b", "-543585,2378805", "-b", "-393585,2228805"]
    r = runner.invoke(cli.entrypoint, args, catch_exceptions=False)
    assert r.exit_code == 0
    lines = r.output.strip().splitlines()
    assert lines[0] == "h,v,ulx,uly,lrx,lry"
    assert len(lines) == 1 + 4
    # shards partition the full list
    rows = set(lines[1:])
    sharded = []
    for i in range(3):
        ri = runner.invoke(cli.entrypoint, args + ["-s", f"{i}/3"],
                           catch_exceptions=False)
        assert ri.exit_code == 0
        sharded.extend(ri.output.strip().splitlines()[1:])
    assert set(sharded) == rows and len(sharded) == len(rows)
    # each row's tile center round-trips through grid.tile
    h, v, ulx, uly, lrx, lry = lines[1].split(",")
    t = grid.tile((float(ulx) + float(lrx)) / 2, (float(uly) + float(lry)) / 2)
    assert (t["h"], t["v"]) == (int(h), int(v))


class FakeDevice:
    def __init__(self, limit):
        self._limit = limit

    def memory_stats(self):
        return {"bytes_limit": self._limit} if self._limit else {}


def test_auto_chips_per_batch_sizes_from_device_memory():
    """VERDICT r1 weak #5: chips_per_batch auto-sizes from the device
    memory budget and the acquired range instead of a static config."""
    from firebird_tpu.ccd import kernel
    from firebird_tpu.driver.core import (auto_chips_per_batch, estimate_obs,
                                          resolve_batching)

    cfg = Config(chips_per_batch=0)
    acq = "1982-01-01/2017-12-31"
    # a 16 GB HBM device fits several chips of the full-archive workload
    n16 = auto_chips_per_batch(cfg, acq, device=FakeDevice(16e9))
    n8 = auto_chips_per_batch(cfg, acq, device=FakeDevice(8e9))
    assert n16 >= 2 * n8 >= 2
    # shorter archives -> smaller working set -> bigger batches
    n_short = auto_chips_per_batch(cfg, "1998-01-01/1999-12-31",
                                   device=FakeDevice(16e9))
    assert n_short > n16
    # the estimate honors the packer's max_obs ceiling
    assert estimate_obs(acq, cfg) == cfg.max_obs
    assert estimate_obs("1998-01-01/1998-06-01", cfg) == cfg.obs_bucket
    # max_obs=0 is the packer's "uncapped", NOT a zero cap: the full
    # archive estimate must stay ~1700 obs, not collapse to 0
    assert estimate_obs(acq, Config(chips_per_batch=0, max_obs=0)) > 1600
    # budget math is consistent with the working-set model
    t = estimate_obs(acq, cfg)
    assert n16 == max(1, int(16e9 * 0.6 / kernel.working_set_bytes(t)))
    # no memory stats (CPU) -> static default; explicit setting -> no-op
    assert auto_chips_per_batch(cfg, acq, device=FakeDevice(None)) == \
        Config.chips_per_batch
    assert resolve_batching(Config(chips_per_batch=5), acq).chips_per_batch == 5


def test_auto_chips_per_batch_grows_with_init_kernel(monkeypatch):
    """The fused INIT kernel never materializes the [P,W,T] one-hot
    window peak, so f32 batch sizing packs more chips — while f64 sizing
    keeps the term (the Mosaic route is f32-on-TPU only)."""
    from firebird_tpu.ccd import kernel
    from firebird_tpu.driver.core import auto_chips_per_batch

    cfg = Config(chips_per_batch=0)
    acq = "1982-01-01/2017-12-31"
    monkeypatch.delenv("FIREBIRD_PALLAS", raising=False)
    base = auto_chips_per_batch(cfg, acq, device=FakeDevice(16e9))
    base_ws64 = kernel.working_set_bytes(512, dtype_bytes=8)
    monkeypatch.setenv("FIREBIRD_PALLAS", "init")
    assert auto_chips_per_batch(cfg, acq, device=FakeDevice(16e9)) > base
    assert kernel.working_set_bytes(512, dtype_bytes=8) == base_ws64


def test_auto_chips_per_batch_grows_with_mega(monkeypatch):
    """The whole-loop mega kernel skips the [P,W,T] one-hot peak like the
    init config, so f32 batch sizing grows vs the XLA path — but NOT past
    the init config: the prologue's [P,B,T]-scale float peak runs
    identically in every config and stays the sizing constraint."""
    from firebird_tpu.ccd import kernel
    from firebird_tpu.driver.core import auto_chips_per_batch

    cfg = Config(chips_per_batch=0)
    acq = "1982-01-01/2017-12-31"
    monkeypatch.delenv("FIREBIRD_PALLAS", raising=False)
    base = auto_chips_per_batch(cfg, acq, device=FakeDevice(16e9))
    base_ws64 = kernel.working_set_bytes(512, dtype_bytes=8)
    monkeypatch.setenv("FIREBIRD_PALLAS", "init")
    with_init = auto_chips_per_batch(cfg, acq, device=FakeDevice(16e9))
    monkeypatch.setenv("FIREBIRD_PALLAS", "mega")
    with_mega = auto_chips_per_batch(cfg, acq, device=FakeDevice(16e9))
    assert with_mega > base
    assert with_mega == with_init
    assert kernel.working_set_bytes(512, dtype_bytes=8) == base_ws64
