"""Format layer tests, mirroring the reference's golden format fixture
(test/test_pyccd.py:37-126)."""

import datetime

import numpy as np

from firebird_tpu.ccd import format as fmt
from firebird_tpu.ccd import params


def test_format_golden():
    """Hand-built ccdresult -> exact expected row (the reference's golden
    test, adapted: same fields, same date conversion, same flattening)."""
    fval = 0.5
    sday, eday, bday = 1, 3, 2
    band_model = {"magnitude": fval, "rmse": fval,
                  "coefficients": (fval, fval), "intercept": fval}
    cm = {"start_day": sday, "end_day": eday, "break_day": bday,
          "observation_count": 3, "change_probability": fval,
          "curve_qa": fval,
          **{name: band_model for name in params.BAND_NAMES}}
    rows = fmt.format_records(
        cx=100, cy=-100, px=50, py=-50, dates=[sday, bday, eday],
        ccdresult={"processing_mask": [0, 1, 0], "change_models": [cm]})

    iso = lambda o: datetime.date.fromordinal(o).isoformat()
    expected = {"cx": 100, "cy": -100, "px": 50, "py": -50,
                "sday": iso(sday), "eday": iso(eday), "bday": iso(bday),
                "chprob": fval, "curqa": fval,
                "dates": [iso(sday), iso(bday), iso(eday)],
                "mask": [0, 1, 0]}
    for p in fmt.BAND_PREFIX:
        expected[f"{p}mag"] = fval
        expected[f"{p}rmse"] = fval
        expected[f"{p}coef"] = (fval, fval)
        expected[f"{p}int"] = fval
    assert rows[0] == expected


def test_format_default_sentinel():
    """No change models -> sentinel row sday=eday=bday=day 1
    (ccdc/pyccd.py:99-103)."""
    rows = fmt.format_records(cx=1, cy=2, px=3, py=4, dates=[5, 6],
                              ccdresult={"change_models": [],
                                         "processing_mask": [0, 0]})
    assert len(rows) == 1
    assert rows[0]["sday"] == rows[0]["eday"] == rows[0]["bday"] == "0001-01-01"
    assert rows[0]["chprob"] is None
    assert rows[0]["blcoef"] is None


def test_default_passthrough():
    assert fmt.default([]) == [{"start_day": 1, "end_day": 1, "break_day": 1}]
    assert fmt.default(["x"]) == ["x"]
