"""Active-lane compaction: row-identical results, real lane savings.

The compaction machinery (kernel._detect_batch_impl: dense-prefix
permutation carried in the loop state, per-block skip guards, bucketed
re-entry) must be INVISIBLE in results — every per-lane decision is
permutation-invariant, and the carried permutation is inverted at loop
exit.  These tests pin compact-on vs compact-off to exact equality on
synthetic and fuzz-adversarial workloads including the edge cases
(everything done before round 1, a single alive pixel, alive count
exactly on the re-entry bucket boundary), check the occupancy capture
and telemetry, and prove the driver's resume-after-quarantine path is
store-identical under compaction (slow-marked; `make compact-smoke` is
the fast on-vs-off store proof).
"""

import dataclasses
import os

import numpy as np
import jax.numpy as jnp
import pytest

from firebird_tpu.ccd import flops, kernel, params, synthetic
from firebird_tpu.ingest.packer import PackedChips

P_TEST = 32      # every kernel case shares one compiled shape pair


@pytest.fixture(autouse=True, scope="module")
def _small_cascade_env():
    """Let the P=32 cases build the bucketed re-entry loop (production
    gates it at FIREBIRD_COMPACT_MIN_LANES=1024 to keep tiny-shape
    compiles cheap).  Module-scoped and set before the first compile of
    this module's (unique) shapes; trace-time read."""
    old = os.environ.get("FIREBIRD_COMPACT_MIN_LANES")
    os.environ["FIREBIRD_COMPACT_MIN_LANES"] = "8"
    yield
    if old is None:
        os.environ.pop("FIREBIRD_COMPACT_MIN_LANES", None)
    else:
        os.environ["FIREBIRD_COMPACT_MIN_LANES"] = old


def _grid():
    return synthetic.acquisition_dates("1995-01-01", "2000-01-01", 16)


def _std_pixel(rng, t, brk=False):
    Y = synthetic.harmonic_series(t, rng)
    if brk:
        Y[:, t.shape[0] // 2:] += 800.0
    return Y, np.full(t.shape[0], synthetic.QA_CLEAR, np.uint16)


def _fill_pixel(t):
    return (np.full((7, t.shape[0]), params.FILL_VALUE, np.float64),
            np.full(t.shape[0], synthetic.QA_FILL, np.uint16))


def _pack(t, pixels):
    Ys, qas = zip(*pixels)
    spectra = np.stack([np.asarray(Y, np.int16) for Y in Ys])
    spectra = spectra.transpose(1, 0, 2)[None]
    return PackedChips(cids=np.zeros((1, 2), np.int64),
                       dates=t[None].astype(np.int32),
                       spectra=spectra, qas=np.stack(qas)[None],
                       n_obs=np.array([t.shape[0]], np.int32))


def _run_pair(p, dtype=jnp.float64):
    on = kernel.detect_packed(p, dtype=dtype, compact=True)
    off = kernel.detect_packed(p, dtype=dtype, compact=False)
    return on, off


def _assert_identical(on, off):
    """Results (not diagnostics) must match bit for bit: segments, days,
    QA, coefficients, magnitudes, masks, procedures."""
    for f in ("n_segments", "seg_meta", "seg_rmse", "seg_mag", "seg_coef",
              "mask", "procedure", "rounds", "round_counts", "vario"):
        np.testing.assert_array_equal(np.asarray(getattr(on, f)),
                                      np.asarray(getattr(off, f)),
                                      err_msg=f)


def _mixed_pixels(n_std=8, seed=7):
    """n_std standard pixels (half with breaks) scattered among fill
    lanes — DONE-from-round-0 lanes interleave with long-lived ones, so
    the dense-prefix permutation actually moves rows."""
    rng = np.random.default_rng(seed)
    t = _grid()
    pixels = [_std_pixel(rng, t, brk=i % 2 == 0) for i in range(n_std)]
    pixels += [_fill_pixel(t) for _ in range(P_TEST - n_std)]
    order = rng.permutation(P_TEST)
    return t, [pixels[i] for i in order]


def test_compact_row_identical_mixed():
    """The headline contract on a heterogeneous chip — and with 8
    standard pixels against the bucket of pow2(32/8)=8 lanes, the alive
    count sits EXACTLY on the re-entry boundary, so the cascade slices a
    full bucket (the off-by-one hot spot)."""
    t, pixels = _mixed_pixels(n_std=8)
    on, off = _run_pair(_pack(t, pixels))
    _assert_identical(on, off)
    # the cascade case really compacted and really captured occupancy
    assert int(np.asarray(on.compactions)[0]) > 0
    occ = np.asarray(on.occupancy)[0]
    r = int(np.asarray(on.rounds)[0])
    assert (occ[:r, 0] > 0).all()          # active lanes every round
    assert (occ[r:] == 0).all()            # rows past the loop are zero
    # compact-off pays the full width every round
    occ_off = np.asarray(off.occupancy)[0]
    assert (occ_off[:r, 1] == P_TEST).all()


def test_compact_row_identical_single_alive_pixel():
    rng = np.random.default_rng(3)
    t = _grid()
    pixels = [_fill_pixel(t) for _ in range(P_TEST)]
    pixels[17] = _std_pixel(rng, t, brk=True)
    on, off = _run_pair(_pack(t, pixels))
    _assert_identical(on, off)
    assert int(np.asarray(on.n_segments)[0, 17]) >= 1


def test_compact_all_done_before_round_one():
    """Every pixel resolved by the prologue (fill -> no-data): the loop
    body never runs, occupancy stays empty, results still identical."""
    t = _grid()
    on, off = _run_pair(_pack(t, [_fill_pixel(t) for _ in range(P_TEST)]))
    _assert_identical(on, off)
    assert int(np.asarray(on.rounds)[0]) == 0
    assert int(np.asarray(on.compactions)[0]) == 0
    assert (np.asarray(on.occupancy) == 0).all()


def test_compact_row_identical_fuzz_subset():
    """Adversarial pixels (the fuzz generator's QA mixes, spikes, step
    changes, range violations) through the same shared shape — compact
    on/off must agree bit for bit on every field."""
    from tests.test_fuzz_parity import SPECIALS, _fuzz_pixel

    rng = np.random.default_rng(606)
    t = _grid()
    pixels = [_fuzz_pixel(t, rng, special=SPECIALS.get(i))
              for i in range(P_TEST)]
    on, off = _run_pair(_pack(t, pixels))
    _assert_identical(on, off)


def test_occupancy_detail_and_wasted_reduction():
    """The occupancy model: compact-off pays padded lanes every round;
    compact-on's effective lane-rounds track the active set (trailing
    dead blocks skipped, bucket re-entry for the tail), so wasted
    lane-rounds drop — on this mixed workload by far more than the 2x
    acceptance bar."""
    t, pixels = _mixed_pixels(n_std=8, seed=11)
    on, off = _run_pair(_pack(t, pixels))
    d_on = flops.occupancy_detail(np.asarray(on.occupancy),
                                  np.asarray(on.rounds), P_TEST)
    d_off = flops.occupancy_detail(np.asarray(off.occupancy),
                                   np.asarray(off.rounds), P_TEST)
    assert d_on["active_lane_rounds"] == d_off["active_lane_rounds"]
    assert d_off["effective_lane_rounds"] == d_off["padded_lane_rounds"]
    assert d_on["wasted_lane_rounds"] * 2 <= d_off["wasted_lane_rounds"]
    assert d_on["per_round"][0]["paid"] <= P_TEST
    assert "_fractions" in d_on           # histogram feed


def test_record_occupancy_feeds_registry():
    from firebird_tpu.obs import metrics as obs_metrics

    obs_metrics.reset_registry()
    t, pixels = _mixed_pixels(n_std=8, seed=19)
    on, _ = _run_pair(_pack(t, pixels))
    host = kernel.ChipSegments(*[
        None if getattr(on, f.name) is None else np.asarray(getattr(on, f.name))
        for f in dataclasses.fields(on)])
    det = kernel.record_occupancy(host)
    assert det is not None and "_fractions" not in det
    assert obs_metrics.counter("kernel_compactions").value > 0
    assert obs_metrics.counter("kernel_active_lane_rounds").value \
        == det["active_lane_rounds"]
    assert obs_metrics.counter("kernel_wasted_lane_rounds").value \
        == det["wasted_lane_rounds"]
    h = obs_metrics.histogram("kernel_round_active_fraction",
                              buckets=kernel.FRACTION_BUCKETS)
    assert h.snapshot()["count"] > 0
    # pre-compaction artifacts (occupancy=None) are a no-op, not a crash
    legacy = dataclasses.replace(host, occupancy=None)
    assert kernel.record_occupancy(legacy) is None
    obs_metrics.reset_registry()


def test_expected_compaction_speedup_model():
    assert flops.expected_compaction_speedup(1.0) == pytest.approx(1.0, abs=0.03)
    assert flops.expected_compaction_speedup(0.5) == pytest.approx(2.0, rel=0.05)
    # floor: a single block is the narrowest the guards can pay
    assert flops.expected_compaction_speedup(0.0, lanes=10000) \
        == pytest.approx(10000 / 512, rel=0.01)


def test_compact_knob_resolution():
    """FIREBIRD_COMPACT / Config.compact contract."""
    from firebird_tpu.config import Config

    assert params.compact_default() in (True, False)
    old = os.environ.get("FIREBIRD_COMPACT")
    try:
        os.environ["FIREBIRD_COMPACT"] = "0"
        assert not params.compact_default()
        assert not Config.from_env().compact
        os.environ["FIREBIRD_COMPACT"] = "1"
        assert params.compact_default()
        assert Config.from_env().compact
    finally:
        if old is None:
            os.environ.pop("FIREBIRD_COMPACT", None)
        else:
            os.environ["FIREBIRD_COMPACT"] = old
    assert params.compact_every() >= 1
    assert 0.0 <= params.compact_floor() <= 1.0


@pytest.mark.slow
def test_resume_after_quarantine_with_compaction(tmp_path):
    """Driver-level: a poisoned chip quarantined under compaction-ON,
    then resume — the final store is row-for-row identical to a clean
    compaction-OFF run (on-vs-off AND resume equivalence in one; the
    fast path of this proof is `make compact-smoke`)."""
    from firebird_tpu import grid
    from firebird_tpu.config import Config
    from firebird_tpu.driver import core
    from firebird_tpu.driver import quarantine as qlib
    from firebird_tpu.ingest import SyntheticSource
    from firebird_tpu.store import SqliteStore
    from firebird_tpu.utils.fn import take
    from tools.chaos_soak import store_rows

    ACQ = "1995-01-01/1997-06-01"     # matches test_driver's jit cache
    src = lambda: SyntheticSource(seed=0)
    cids = list(take(2, grid.chips(grid.tile(x=100, y=200))))
    poisoned = cids[0]

    clean_cfg = Config(store_backend="sqlite",
                       store_path=str(tmp_path / "clean.db"),
                       source_backend="synthetic", chips_per_batch=1,
                       dtype="float64", device_sharding="off",
                       compact=False)
    core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                         chunk_size=2, cfg=clean_cfg, source=src())
    clean = store_rows(SqliteStore(clean_cfg.store_path,
                                   clean_cfg.keyspace()))

    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "compact.db"),
                 source_backend="synthetic", chips_per_batch=1,
                 dtype="float64", device_sharding="off", fetch_retries=0,
                 compact=True,
                 faults=f"ingest:chip={poisoned[0]}:{poisoned[1]}")
    done = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                                chunk_size=2, cfg=cfg, source=src())
    assert list(done) == [cids[1]]
    qpath = qlib.quarantine_path(cfg)
    assert len(qlib.Quarantine.load(qpath)) == 1

    healed = Config(**{**cfg.__dict__, "faults": ""})
    out = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                               chunk_size=2, cfg=healed, source=src(),
                               resume=True)
    assert set(out) == set(cids)
    assert len(qlib.Quarantine.load(qpath)) == 0
    compacted = store_rows(SqliteStore(cfg.store_path, cfg.keyspace()))
    for table in ("chip", "pixel", "segment"):
        assert clean[table] == compacted[table], table
