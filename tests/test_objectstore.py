"""Object-tier tests (store/objectstore.py, docs/ROBUSTNESS.md "Object
tier"): the chunked conditional-put protocol, torn-upload generation
fallback, the orphan scrubber, the Store/statestore/pyramid refactors
behind it, and the two nastiest windows — a SIGKILL between the last
chunk upload and the manifest commit, and a zombie's stale-fence
conditional put racing its successor."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from firebird_tpu import faults as faultlib
from firebird_tpu.config import Config
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.store import open_store
from firebird_tpu.store.objectstore import (KEEP_GENERATIONS,
                                            LocalObjectStore,
                                            MirroredStore,
                                            ObjectBackedStore,
                                            PreconditionFailed,
                                            RetryingObjectStore,
                                            StaleObjectFence, cas_update,
                                            open_object_root,
                                            scope_for_path)


def seg_frame(cx=1, cy=2, px=3, py=4, sday="1999-01-01", chprob=1.0):
    f = {"cx": [cx], "cy": [cy], "px": [px], "py": [py],
         "sday": [sday], "eday": ["2000-01-01"], "bday": [sday],
         "chprob": [chprob], "curqa": [8], "rfrawp": [None]}
    for p in ("bl", "gr", "re", "ni", "s1", "s2", "th"):
        f[f"{p}mag"] = [1.5]
        f[f"{p}rmse"] = [0.5]
        f[f"{p}coef"] = [[0.1, 0.2, 0.3]]
        f[f"{p}int"] = [7.0]
    return f


# ---------------------------------------------------------------------------
# Protocol
# ---------------------------------------------------------------------------

def test_chunked_roundtrip_and_meta(tmp_path):
    s = LocalObjectStore(str(tmp_path), chunk_size=64)
    body = bytes(range(256))                     # 4 distinct chunks
    m = s.put("a/b", body, meta={"rows": 3})
    assert m.generation == 1 and len(m.chunks) == 4 and m.size == 256
    got, meta = s.get("a/b")
    assert got == body and meta.meta == {"rows": 3}
    h = s.head("a/b")
    assert h is not None and h.generation == 1 and h.meta == {"rows": 3}


def test_conditional_put_and_generation_pruning(tmp_path):
    s = LocalObjectStore(str(tmp_path))
    s.put("k", b"one")
    s.put("k", b"two", if_generation=1)
    with pytest.raises(PreconditionFailed) as ei:
        s.put("k", b"late", if_generation=1)
    assert ei.value.current == 2
    assert s.get("k")[0] == b"two"
    # if_generation=0 means "must not exist"
    with pytest.raises(PreconditionFailed):
        s.put("k", b"new", if_generation=0)
    s.put("fresh", b"x", if_generation=0)
    # only KEEP_GENERATIONS manifests are retained
    for i in range(5):
        s.put("k", f"v{i}".encode())
    kdir = s._kdir("k")
    manifests = [n for n in os.listdir(kdir) if n.endswith(".json")]
    assert len(manifests) == KEEP_GENERATIONS


def test_list_delete_and_key_quoting(tmp_path):
    s = LocalObjectStore(str(tmp_path))
    keys = ["a/b c", "a/d%2F", "z/1"]
    for k in keys:
        s.put(k, k.encode())
    assert s.list("a/") == sorted(keys[:2])
    assert s.list() == sorted(keys)
    for k in keys:                               # quoting round-trips
        assert s.get(k)[0] == k.encode()
    s.delete("a/b c")
    assert s.head("a/b c") is None
    assert s.list("a/") == ["a/d%2F"]
    s.delete("a/b c")                            # idempotent


def test_torn_chunk_falls_back_one_generation(tmp_path):
    obs_metrics.reset_registry()
    s = LocalObjectStore(str(tmp_path), chunk_size=32)
    good = bytes(range(100))
    s.put("k", good)
    s.put("k", bytes(reversed(range(100))), _torn="chunk")
    got, meta = s.get("k")
    assert got == good and meta.generation == 1
    assert obs_metrics.counter("objectstore_torn_recoveries").value >= 1
    # head still reports the (torn) newest committed generation — the
    # conditional-put expectation readers must NOT take from get()
    assert s.head("k").generation == 2


def test_torn_manifest_is_invisible_and_scrubbed(tmp_path):
    s = LocalObjectStore(str(tmp_path), chunk_size=32)
    s.put("k", b"\x01" * 100, _torn="manifest")
    assert s.head("k") is None
    assert s.list() == []
    c = s.census()
    assert c["orphan_chunks"] >= 1 and c["keys"] == 0
    # inside the grace window the orphans are a live writer's chunks
    rep = s.scrub(grace_sec=3600)
    assert rep["removed"] == 0 and rep["kept_young"] >= 1
    rep = s.scrub(grace_sec=0.0, dry_run=True)
    assert rep["removed"] >= 1 and s.census()["orphan_chunks"] >= 1
    rep = s.scrub(grace_sec=0.0)
    assert rep["removed"] >= 1 and s.census()["orphan_chunks"] == 0


def test_scrub_keeps_referenced_chunks(tmp_path):
    s = LocalObjectStore(str(tmp_path), chunk_size=32)
    body = bytes(range(100))
    s.put("live", body)
    s.put("gone", b"\x02" * 100, _torn="manifest")
    s.scrub(grace_sec=0.0)
    assert s.get("live")[0] == body


def test_census_tolerates_junk(tmp_path):
    s = LocalObjectStore(str(tmp_path))
    s.put("k", b"x")
    kdir = s._kdir("k")
    with open(os.path.join(kdir, "g2.json"), "w") as f:
        f.write("{not json")
    os.makedirs(os.path.join(str(tmp_path), "keys", "stray"),
                exist_ok=True)
    c = s.census()
    assert c["keys"] == 1 and c["junk"] >= 1
    assert s.get("k")[0] == b"x"                 # junk newest falls back


def test_cas_update_contends_past_torn_newest(tmp_path):
    s = LocalObjectStore(str(tmp_path))
    cas_update(s, "ctr", lambda old: b"1" if old is None else
               str(int(old) + 1).encode())
    cas_update(s, "ctr", lambda old: str(int(old) + 1).encode())
    assert s.get("ctr")[0] == b"2"
    # a torn newest must not livelock the RMW loop: head says gen 3,
    # get falls back to gen 2 — the expectation must come from head
    s.put("ctr", b"9", _torn="chunk")
    cas_update(s, "ctr", lambda old: str(int(old) + 1).encode())
    assert s.get("ctr")[0] == b"3"


def test_retry_and_fault_layering(tmp_path, monkeypatch):
    """open_object_root wires Local -> Faulty -> Retrying: transient
    injected faults are retried away; torn faults pass through
    NonRetryable with the damage preserved."""
    from firebird_tpu import retry as retrylib

    monkeypatch.setattr(retrylib.time, "sleep", lambda s: None)
    obs_metrics.reset_registry()
    root = str(tmp_path / "objects")
    cfg = Config.from_env(env=dict(
        os.environ, FIREBIRD_OBJECT_ROOT=root,
        FIREBIRD_FAULTS="object:p=0.4,seed=3", FIREBIRD_RETRIES="8"))
    s = open_object_root(cfg=cfg)
    assert isinstance(s, RetryingObjectStore)
    for i in range(10):
        s.put(f"k{i}", b"v")
    assert sorted(s.list()) == sorted(f"k{i}" for i in range(10))
    assert all(s.get(f"k{i}")[0] == b"v" for i in range(10))
    assert obs_metrics.counter("objectstore_retries").value >= 1

    torn_cfg = Config.from_env(env=dict(
        os.environ, FIREBIRD_OBJECT_ROOT=root,
        FIREBIRD_FAULTS="object:p=1,torn"))
    t = open_object_root(cfg=torn_cfg)
    with pytest.raises(faultlib.TornUpload):
        t.put("k0", b"replacement")
    assert s.get("k0")[0] == b"v"                # fallback, not retry-put


def test_faults_grammar_object_scope():
    plan = faultlib.FaultPlan.parse("object:p=0.5,torn")
    assert plan.injector("object") is not None
    with pytest.raises(ValueError):              # torn is object-only
        faultlib.FaultPlan.parse("store:p=1,torn")
    with pytest.raises(ValueError):              # chip= never fires here
        faultlib.FaultPlan.parse("object:chip=1:2")


# ---------------------------------------------------------------------------
# Nasty window 1: SIGKILL between the last chunk upload and the commit
# ---------------------------------------------------------------------------

CHILD_SRC = """\
import os, sys
sys.path.insert(0, os.environ["FB_REPO"])
from firebird_tpu.store.objectstore import LocalObjectStore
s = LocalObjectStore(os.environ["FIREBIRD_OBJECT_ROOT"], chunk_size=64)
s.put("w/key", b"".join(bytes([c]) * 64 for c in range(4)))
"""


def test_sigkill_between_chunks_and_manifest(tmp_path):
    root = str(tmp_path / "objects")
    env = dict(os.environ, FB_REPO=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir),
        FIREBIRD_OBJECT_ROOT=root,
        FIREBIRD_OBJECT_COMMIT_HOLD_SEC="60")
    child = subprocess.Popen([sys.executable, "-c", CHILD_SRC], env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    try:
        chunk_dir = os.path.join(root, "chunks")
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                n = len([x for x in os.listdir(chunk_dir)
                         if not x.endswith(".tmp")])
            except OSError:
                n = 0
            if n >= 4:
                break
            assert child.poll() is None, \
                f"writer finished despite hold: {child.stdout.read()}"
            time.sleep(0.02)
        else:
            pytest.fail("chunks never appeared")
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
        child.stdout.close()
    s = LocalObjectStore(root, chunk_size=64)
    assert s.head("w/key") is None               # no visible partial
    assert s.list() == []
    assert s.census()["orphan_chunks"] == 4
    assert s.scrub(grace_sec=0.0)["removed"] == 4
    m = s.put("w/key", b"clean")                 # successor recovers
    assert m.generation == 1 and s.get("w/key")[0] == b"clean"


# ---------------------------------------------------------------------------
# Nasty window 2: the zombie's stale-fence conditional put
# ---------------------------------------------------------------------------

def test_stale_object_fence_rejected_durably(tmp_path):
    obs_metrics.reset_registry()
    root = str(tmp_path / "objects")

    def make():
        return ObjectBackedStore(open_object_root(
            root=root, cfg=Config.from_env(env=dict(
                os.environ, FIREBIRD_OBJECT_ROOT=root))),
            "scope", "ks")

    zombie, successor = make(), make()
    zombie.bind_fence(3)
    successor.bind_fence(5)
    zombie.write("segment", seg_frame(chprob=0.1))   # pre-reclaim: lands
    successor.write("segment", seg_frame(chprob=0.9))
    with pytest.raises(StaleObjectFence):
        zombie.write("segment", seg_frame(chprob=0.2))
    assert successor.read("segment")["chprob"] == [0.9]
    assert successor.fence_rejects() == 1
    assert obs_metrics.counter("object_fence_rejected_total").value == 1
    zombie.close()
    successor.close()
    assert make().fence_rejects() == 1           # durable across opens


def test_fenced_store_stamps_object_fence(tmp_path):
    """fleet.FencedStore binds the lease fence onto a mirror/object
    store, so the object layer rejects a zombie even when the queue's
    own fence_valid check cannot run."""
    from firebird_tpu.fleet.queue import FencedStore, FleetQueue

    q = FleetQueue(str(tmp_path / "q.db"), lease_sec=30)
    q.enqueue("detect", {"n": 1})
    lease = q.claim("w:1")
    root = str(tmp_path / "objects")
    inner = ObjectBackedStore(open_object_root(
        root=root, cfg=Config.from_env(env=dict(
            os.environ, FIREBIRD_OBJECT_ROOT=root))), "scope", "ks")
    FencedStore(inner, q, lease)
    assert inner._fence == lease.fence
    inner.close()
    q.close()


# ---------------------------------------------------------------------------
# The Store refactor: pure object backend + the write-through mirror
# ---------------------------------------------------------------------------

def fixture_rows(store):
    store.write("chip", {"cx": [10], "cy": [20],
                         "dates": [["1999-01-01", "1999-02-01"]]})
    store.write("pixel", {"cx": [10], "cy": [20], "px": [10], "py": [20],
                          "mask": [[1, 0]]})
    store.write("segment", seg_frame(cx=10, cy=20, chprob=0.25))
    store.write("segment", seg_frame(cx=10, cy=20, chprob=0.75))
    store.write("tile", {"tx": [1], "ty": [2], "name": ["rf"],
                         "model": ["BLOB"], "updated": ["2020-01-01"]})


def canon(store) -> dict:
    out = {}
    for t in ("chip", "pixel", "segment", "tile"):
        frame = store.read(t)
        cols = sorted(frame)
        n = len(frame[cols[0]]) if cols else 0
        out[t] = sorted(
            json.dumps([(c, frame[c][i]) for c in cols], sort_keys=True)
            for i in range(n))
    return out


def test_object_backend_parity_with_sqlite(tmp_path, monkeypatch):
    monkeypatch.delenv("FIREBIRD_OBJECT_ROOT", raising=False)
    sq = open_store("sqlite", str(tmp_path / "s.db"), "ks")
    fixture_rows(sq)
    want = canon(sq)
    counts = {t: sq.count(t) for t in want}
    sq.close()
    monkeypatch.setenv("FIREBIRD_OBJECT_ROOT", str(tmp_path / "objects"))
    ob = open_store("object", str(tmp_path / "scope"), "ks")
    fixture_rows(ob)
    assert canon(ob) == want
    assert {t: ob.count(t) for t in want} == counts  # head-only counts
    assert ob.chip_ids("segment") == {(10, 20)}
    assert ob.read("segment", {"cx": 10, "cy": 20})["chprob"] == [0.75]
    empty = ob.read("segment", {"cx": 99})
    assert all(v == [] for v in empty.values())
    ob.close()


def test_open_store_mirror_is_env_driven(tmp_path, monkeypatch):
    monkeypatch.setenv("FIREBIRD_OBJECT_ROOT", str(tmp_path / "objects"))
    path = str(tmp_path / "m.db")
    st = open_store("sqlite", path, "ks")
    assert isinstance(st, MirroredStore)
    fixture_rows(st)
    want = canon(st)                             # reads are local
    st.close()
    # the object side alone carries identical rows
    ob = ObjectBackedStore(
        open_object_root(root=str(tmp_path / "objects")),
        scope_for_path(path), "ks")
    assert canon(ob) == want
    ob.close()
    # read-only replicas skip the wrap (they never write)
    ro = open_store("sqlite", path, "ks", read_only=True)
    assert not isinstance(ro, MirroredStore)
    ro.close()
    monkeypatch.delenv("FIREBIRD_OBJECT_ROOT")
    st = open_store("sqlite", str(tmp_path / "m2.db"), "ks")
    assert not isinstance(st, MirroredStore)
    st.close()


def test_config_validates_object_knobs():
    with pytest.raises(ValueError):
        Config.from_env(env={"FIREBIRD_STORE_BACKEND": "object"})
    with pytest.raises(ValueError):
        Config.from_env(env={"FIREBIRD_OBJECT_ROOT": "/tmp/o",
                             "FIREBIRD_OBJECT_CHUNK_KB": "0"})
    cfg = Config.from_env(env={"FIREBIRD_STORE_BACKEND": "object",
                               "FIREBIRD_OBJECT_ROOT": "/tmp/o"})
    assert cfg.object_chunk_kb == 256


# ---------------------------------------------------------------------------
# Statestore + pyramid seams
# ---------------------------------------------------------------------------

def _chip():
    from firebird_tpu import grid

    return tuple(int(v) for v in
                 next(iter(grid.chips(grid.tile(x=100.0, y=200.0)))))


def _arrays(P=4, B=2, K=3):
    from firebird_tpu.streamops.statestore import _layout

    out = {}
    for i, (name, dtype, shape) in enumerate(_layout(P, B, K)):
        n = max(int(np.prod(shape)), 1)
        out[name] = ((np.arange(n) + i) % 5).astype(dtype).reshape(shape)
    return out


def test_object_statestore_parity(tmp_path):
    from firebird_tpu.streamops.statestore import (ObjectStateStore,
                                                   TileStateStore)

    cid = _chip()
    arrays = _arrays()
    packed = TileStateStore(str(tmp_path / "packed"))
    objst = ObjectStateStore(
        open_object_root(root=str(tmp_path / "objects")), "sc")
    packed.save_arrays(cid, arrays)
    objst.save_arrays(cid, arrays)
    a, b = packed.peek_arrays(cid), objst.peek_arrays(cid)
    for k in arrays:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k
    assert objst.peek_horizon(cid) == packed.peek_horizon(cid)
    assert objst.exists(cid) and objst.chips() == [cid]
    objst.void(cid)
    assert not objst.exists(cid)
    packed.close()
    objst.close()


def test_open_statestore_mirrors_under_object_root(tmp_path):
    from firebird_tpu.streamops.statestore import (MirroredStateStore,
                                                   open_statestore)

    cfg = Config.from_env(env={
        "FIREBIRD_STORE_PATH": str(tmp_path / "s.db"),
        "FIREBIRD_STREAM_DIR": str(tmp_path / "stream"),
        "FIREBIRD_OBJECT_ROOT": str(tmp_path / "objects")})
    st = open_statestore(cfg)
    assert isinstance(st, MirroredStateStore)
    cid = _chip()
    st.save_arrays(cid, _arrays())
    assert st.exists(cid)                        # local read-authoritative
    assert st._mirror.exists(cid)                # mirrored
    assert st.status()["backend"] == "packed+object"
    st.close()
    # npz/f64 escape hatch is NOT mirrored (lossy payloads)
    cfg64 = Config.from_env(env={
        "FIREBIRD_STORE_PATH": str(tmp_path / "s.db"),
        "FIREBIRD_STREAM_DIR": str(tmp_path / "stream64"),
        "FIREBIRD_DTYPE": "float64",
        "FIREBIRD_OBJECT_ROOT": str(tmp_path / "objects")})
    st = open_statestore(cfg64)
    assert not isinstance(st, MirroredStateStore)
    st.close()


def test_object_tile_storage_contract(tmp_path):
    from firebird_tpu.serve import pyramid as pyrlib

    fills = {"v": 7}

    def read_chip(name, date, cx, cy):
        return np.full(pyrlib.TILE_SIDE * pyrlib.TILE_SIDE, fills["v"],
                       np.int32)

    objstore = open_object_root(root=str(tmp_path / "objects"))
    storage = pyrlib.ObjectTileStorage(objstore, "sc")
    pyr = pyrlib.TilePyramid("obj", read_chip, storage=storage)
    z, x, y = pyrlib.Z_BASE, 512, 512
    cells, meta = pyr.tile("curveqa", "2020-01-01", z, x, y)
    assert int(cells.ravel()[0]) == 7 and meta["version"] == 1
    ident1 = storage.meta_ident("curveqa", "2020-01-01", z, x, y)
    cx, cy = pyrlib.chips_of_tile(z, x, y)[0]
    assert pyr.invalidate_chip(cx, cy) >= 1
    peek = pyr.peek_meta("curveqa", "2020-01-01", z, x, y)
    assert peek and peek["stale"]
    fills["v"] = 9
    cells, meta = pyr.tile("curveqa", "2020-01-01", z, x, y)
    assert int(cells.ravel()[0]) == 9 and meta["version"] == 2
    assert storage.meta_ident("curveqa", "2020-01-01", z, x, y) != ident1
    peek = pyr.peek_meta("curveqa", "2020-01-01", z, x, y)
    assert peek and not peek["stale"]
    st = pyr.status()
    assert st["root"].startswith("object:")
    assert st["tiles_by_level"][str(z)]["tiles"] == 1
    objstore.close()


def test_pyramid_storage_selector(tmp_path):
    from firebird_tpu.serve import pyramid as pyrlib

    mirror_cfg = Config.from_env(env={
        "FIREBIRD_STORE_PATH": str(tmp_path / "s.db"),
        "FIREBIRD_OBJECT_ROOT": str(tmp_path / "objects")})
    assert pyrlib.pyramid_storage(mirror_cfg, str(tmp_path)) is None
    pure_cfg = Config.from_env(env={
        "FIREBIRD_STORE_BACKEND": "object",
        "FIREBIRD_STORE_PATH": str(tmp_path / "scope"),
        "FIREBIRD_OBJECT_ROOT": str(tmp_path / "objects")})
    storage = pyrlib.pyramid_storage(pure_cfg, str(tmp_path))
    assert isinstance(storage, pyrlib.ObjectTileStorage)
