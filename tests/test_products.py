"""Products layer: the completed `ccdc-save` capability (docs/faq.rst:38-109,
SURVEY.md §2.5).  Product math is tested against hand-built segment frames;
the run modes against a synthetic end-to-end store."""

import numpy as np
import pytest
from click.testing import CliRunner

from firebird_tpu import cli, products
from firebird_tpu.ccd.params import FILL_VALUE
from firebird_tpu.config import Config
from firebird_tpu.ingest.packer import CHIP_SIDE, PIXELS
from firebird_tpu.store import MemoryStore
from firebird_tpu.utils import dates as dt

# A real CONUS chip UL (grid-aligned): snap(1500, 3000) -> (-585, 5805).
CX, CY = -585, 5805


def frame(rows):
    """Segment frame from (px, py, sday, eday, bday, chprob, curqa) rows."""
    cols = ("px", "py", "sday", "eday", "bday", "chprob", "curqa")
    return {c: [r[i] for r in rows] for i, c in enumerate(cols)}


def put_segments(store, rows):
    f = frame(rows)
    n = len(f["px"])
    f["cx"] = [CX] * n
    f["cy"] = [CY] * n
    store.write("segment", f)


# ---------------------------------------------------------------------------
# chip_product math
# ---------------------------------------------------------------------------

def test_seglength_inside_and_after_break():
    # pixel 0: a segment containing D;  pixel 1: D after a confirmed break;
    # pixel 2: sentinel row only (no models).
    p1 = (CX + 30, CY)        # pixel index 1
    p2 = (CX + 60, CY)        # pixel index 2
    seg = frame([
        (CX, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8),
        (p1[0], p1[1], "1995-01-01", "2002-06-01", "2002-06-01", 1.0, 8),
        (p2[0], p2[1], "0001-01-01", "0001-01-01", "0001-01-01", None, None),
    ])
    D = dt.to_ordinal("2005-03-01")
    out = products.chip_product("seglength", D, CX, CY, seg)
    assert out[0] == D - dt.to_ordinal("2000-01-01")
    assert out[1] == D - dt.to_ordinal("2002-06-01")
    assert out[2] == 0
    assert np.all(out[3:] == 0)


def test_ccd_breaks_in_query_year_only():
    p1 = (CX + 30, CY)
    seg = frame([
        # break on 2014-03-01 (doy 60), confirmed
        (CX, CY, "2000-01-01", "2014-02-25", "2014-03-01", 1.0, 8),
        # break in a different year: not reported for 2014
        (p1[0], p1[1], "2000-01-01", "2012-05-01", "2012-05-05", 1.0, 8),
    ])
    D = dt.to_ordinal("2014-07-01")
    out = products.chip_product("ccd", D, CX, CY, seg)
    assert out[0] == 60
    assert out[1] == 0


def test_ccd_ignores_unconfirmed_changes():
    seg = frame([(CX, CY, "2000-01-01", "2014-02-25", "2014-03-01", 0.5, 8)])
    out = products.chip_product("ccd", dt.to_ordinal("2014-07-01"), CX, CY, seg)
    assert out[0] == 0


def test_curveqa_of_containing_segment():
    seg = frame([
        (CX, CY, "2000-01-01", "2005-01-01", "2005-01-01", 1.0, 8),
        (CX, CY, "2005-06-01", "2017-01-01", "2017-01-01", 0.0, 20),
    ])
    assert products.chip_product(
        "curveqa", dt.to_ordinal("2003-01-01"), CX, CY, seg)[0] == 8
    assert products.chip_product(
        "curveqa", dt.to_ordinal("2010-01-01"), CX, CY, seg)[0] == 20
    assert products.chip_product(   # in the gap between segments
        "curveqa", dt.to_ordinal("2005-03-01"), CX, CY, seg)[0] == 0


def test_cover_product_maps_votes_through_classes():
    seg = frame([
        (CX, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8),
        (CX + 30, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8),
        (CX + 60, CY, "0001-01-01", "0001-01-01", "0001-01-01", None, None),
    ])
    # pixel 0 classified (argmax -> index 2), pixel 1 never classified
    seg["rfrawp"] = [[1.0, 3.0, 7.0], None, None]
    D = dt.to_ordinal("2005-01-01")
    out = products.chip_product("cover", D, CX, CY, seg,
                                classes=np.array([4, 6, 9]))
    assert out[0] == 9
    assert out[1] == 0 and out[2] == 0
    with pytest.raises(ValueError, match="class order"):
        products.chip_product("cover", D, CX, CY, seg)


def test_save_cover_end_to_end():
    from firebird_tpu import grid
    from firebird_tpu.rf import forest
    from firebird_tpu.rf.pipeline import save_model

    store = MemoryStore()
    rng = np.random.default_rng(0)
    model = forest.train(rng.normal(0, 1, (60, 33)).astype(np.float32),
                         rng.integers(1, 4, 60), n_trees=5, max_depth=3)
    t = grid.tile(CX, CY)
    save_model(store, t["x"], t["y"], model)
    f = frame([(CX, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8)])
    votes = np.zeros(model.n_classes)
    votes[-1] = 1.0                      # argmax -> last class
    f["rfrawp"] = [votes.tolist()]
    f["cx"], f["cy"] = [CX], [CY]
    store.write("segment", f)
    written = products.save([(CX + 10, CY - 10)], ["cover"], ["2005-06-01"],
                            store=store)
    assert written == [("cover", "2005-06-01", CX, CY)]
    cells = store.read("product", {"name": "cover"})["cells"][0]
    assert cells[0] == int(model.classes[-1])
    assert sum(cells) == int(model.classes[-1])


def test_save_cover_without_model_skips_chip():
    store = MemoryStore()
    f = frame([(CX, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8)])
    f["cx"], f["cy"] = [CX], [CY]
    store.write("segment", f)
    written = products.save([(CX + 10, CY - 10)], ["cover", "curveqa"],
                            ["2005-06-01"], store=store)
    # cover skipped (no trained model stored), curveqa still written
    assert written == [("curveqa", "2005-06-01", CX, CY)]


def test_unknown_product_rejected():
    with pytest.raises(ValueError, match="unknown product"):
        products.chip_product("bogus", 1, CX, CY, frame([]))
    with pytest.raises(ValueError, match="unknown product"):
        products.save([(0, 0)], ["bogus"], ["2014-01-01"],
                      store=MemoryStore())


# ---------------------------------------------------------------------------
# Area selection
# ---------------------------------------------------------------------------

def test_covering_chips_bbox():
    one = products.covering_chips([(CX + 10, CY - 10)])
    assert one == [(CX, CY)]
    # two corners spanning 2x2 chips
    many = products.covering_chips([(CX + 10, CY - 10),
                                    (CX + 3010, CY - 3010)])
    assert set(many) == {(CX, CY), (CX + 3000, CY), (CX, CY - 3000),
                         (CX + 3000, CY - 3000)}


def test_clip_single_point_selects_one_pixel():
    keep = products.clip_mask(CX, CY, [(CX + 95.0, CY - 65.0)])
    assert keep.sum() == 1
    # pixel (row 2, col 3) -> index 2*100+3
    assert keep[2 * CHIP_SIDE + 3]


def test_clip_triangle_subset_of_bbox():
    tri = [(CX, CY), (CX + 1500.0, CY), (CX, CY - 1500.0)]
    keep_tri = products.clip_mask(CX, CY, tri)
    box = [(CX, CY), (CX + 1500.0, CY - 1500.0)]
    keep_box = products.clip_mask(CX, CY, box)
    assert 0 < keep_tri.sum() < keep_box.sum() < PIXELS
    # triangle is roughly half its bounding box
    assert abs(keep_tri.sum() / keep_box.sum() - 0.5) < 0.1


# ---------------------------------------------------------------------------
# The save run (store-backed)
# ---------------------------------------------------------------------------

def test_save_writes_product_rasters_idempotently():
    store = MemoryStore()
    put_segments(store, [
        (CX, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.0, 8),
    ])
    keys = products.save([(CX + 10, CY - 10)], ["seglength", "curveqa"],
                         ["2005-01-01", "2006-01-01"], store=store)
    assert len(keys) == 4
    assert store.count("product") == 4
    # rerun upserts the same keys
    products.save([(CX + 10, CY - 10)], ["seglength", "curveqa"],
                  ["2005-01-01", "2006-01-01"], store=store)
    assert store.count("product") == 4
    got = store.read("product", {"name": "seglength", "date": "2005-01-01"})
    cells = got["cells"][0]
    assert len(cells) == PIXELS
    assert cells[0] == dt.to_ordinal("2005-01-01") - dt.to_ordinal("2000-01-01")


def test_save_clip_masks_outside_pixels():
    store = MemoryStore()
    # segment at pixel (row 2, col 3) — the pixel the clip point selects
    put_segments(store, [
        (CX + 90, CY - 60, "2000-01-01", "2010-01-01", "2010-01-01", 0.0, 8),
    ])
    products.save([(CX + 95.0, CY - 65.0)], ["curveqa"], ["2005-01-01"],
                  clip=True, store=store)
    cells = np.array(store.read("product")["cells"][0])
    assert (cells != FILL_VALUE).sum() == 1
    assert cells[2 * CHIP_SIDE + 3] == 8


def test_save_skips_chips_with_no_segments():
    store = MemoryStore()
    keys = products.save([(CX, CY)], ["ccd"], ["2014-01-01"], store=store)
    assert keys == []
    assert store.count("product") == 0


def test_cli_products_lists_available():
    r = CliRunner().invoke(cli.entrypoint, ["products"])
    assert r.exit_code == 0
    assert set(r.output.split()) == set(products.PRODUCTS)


def test_save_detects_missing_chips_end_to_end():
    """acquired + empty store: save runs change detection first (the
    self-contained ccdc-save shape), then derives products."""
    from firebird_tpu.ingest import SyntheticSource

    store = MemoryStore()
    cfg = Config(store_backend="memory", source_backend="synthetic",
                 chips_per_batch=1, dtype="float64", device_sharding="off",
                 fetch_retries=0)
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    keys = products.save([(100, 200)], ["seglength"], ["1996-06-01"],
                         acquired="1995-01-01/1997-06-01", cfg=cfg,
                         store=store, source=src)
    assert len(keys) == 1
    cells = np.array(store.read("product")["cells"][0])
    assert cells.shape == (PIXELS,)
    # most pixels have been in their first segment since early in the series
    assert (cells > 0).mean() > 0.5


def test_cover_rfidx_accepts_numpy_vote_arrays():
    # rfrawp may hold numpy arrays when no store round-trip intervened;
    # bool(array) raises, so the guard must be None/len-based (ADVICE r1).
    seg = frame([
        (CX, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8),
        (CX + 30, CY, "2000-01-01", "2010-01-01", "2010-01-01", 0.4, 8),
    ])
    seg["rfrawp"] = [np.array([1.0, 3.0, 7.0]), np.array([])]
    a = products.ChipSegmentArrays(CX, CY, seg)
    assert a.rfidx.tolist() == [2, -1]
