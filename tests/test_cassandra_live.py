"""Live-Cassandra integration: write-then-read round trips per table
against a real server — the reference's pattern (test/test_cassandra.py:
22-37, Makefile db-schema + cassandra:3.9 container), which round 1 only
covered through an injected fake session (VERDICT r1 missing #3).

Gated: runs when a Cassandra service is reachable at
$CASSANDRA:$CASSANDRA_PORT (default 127.0.0.1:9043 — the compose
mapping, deploy/docker-compose.yml) AND the cassandra-driver package is
importable; skips cleanly otherwise.  Bring one up with `make db-up
db-schema`, run with `make db-test`.

Environment audit (round 3, VERDICT r2 #7): a live round trip is
IMPOSSIBLE in the build image — no container runtime (docker/podman
absent), no JVM (Cassandra is a Java server), no network egress to pull
either, and even the `cassandra-driver` client package is not baked in.
The in-tree evidence therefore remains the strongest achievable here:
statement-level CQL parity against an injected fake session
(tests/test_store.py::test_cassandra_*), the DDL generator diffed
against the reference's schema.cql (::test_cassandra_schema_parity),
and this file as the ready-to-run live gate for any environment that
has the compose stack.
"""

import os
import socket
import uuid

import pytest


def _live_target():
    host = os.environ.get("CASSANDRA", "127.0.0.1").split(",")[0].strip()
    port = int(os.environ.get("CASSANDRA_PORT", "9043"))
    try:
        import cassandra  # noqa: F401
    except ImportError:
        return None
    try:
        with socket.create_connection((host, port), timeout=2):
            pass
    except OSError:
        return None
    return host, port


@pytest.fixture(scope="module")
def store():
    # Probed lazily (not at import): collection of the wider suite must
    # not pay a TCP connect against a firewalled $CASSANDRA.
    target = _live_target()
    if target is None:
        pytest.skip("no live Cassandra (make db-up db-schema; "
                    "needs cassandra-driver)")
    from firebird_tpu.store import CassandraStore

    host, port = target
    ks = f"fbtest_{uuid.uuid4().hex[:10]}"
    st = CassandraStore(contact_points=[host], port=port, keyspace=ks)
    yield st
    st.session.execute(f"DROP KEYSPACE IF EXISTS {st.keyspace}")
    st.close()


def test_roundtrip_all_tables_live(store):
    from tests.test_store import seg_frame

    store.write("chip", {"cx": [10], "cy": [20],
                         "dates": [["1999-01-01", "1999-02-01"]]})
    store.write("pixel", {"cx": [10], "cy": [20], "px": [10], "py": [20],
                          "mask": [[1, 0]]})
    store.write("segment", seg_frame(cx=10, cy=20))
    store.write("tile", {"tx": [1], "ty": [2], "name": ["rf"],
                         "model": ["BLOB"], "updated": ["2020-01-01"]})
    assert store.read("chip", {"cx": 10, "cy": 20})["dates"][0] == \
        ["1999-01-01", "1999-02-01"]
    assert store.read("pixel")["mask"][0] == [1, 0]
    seg = store.read("segment")
    assert seg["blcoef"][0] == [0.1, 0.2, 0.3]
    assert seg["chprob"][0] == 1.0
    assert store.read("tile")["model"] == ["BLOB"]


def test_upsert_idempotence_live(store):
    """Same PK written twice -> one row, newest value (the reference's
    idempotent-rerun durability model, schema.cql:142)."""
    from tests.test_store import seg_frame

    store.write("segment", seg_frame(cx=77, chprob=0.5))
    store.write("segment", seg_frame(cx=77, chprob=0.9))
    rows = store.read("segment", {"cx": 77, "cy": 2})
    assert len(rows["chprob"]) == 1
    assert rows["chprob"][0] == 0.9


def test_ddl_matches_firebird_schema_command(store):
    """The live schema the store created equals what `firebird schema`
    prints (the Makefile db-schema path) — one source of truth."""
    from firebird_tpu.store import cassandra_ddl

    ddl = cassandra_ddl(store.keyspace)
    names = {s.split("EXISTS ")[1].split(" ", 1)[0].split(".")[-1]
             for s in ddl if "CREATE TABLE" in s}
    ks_meta = store.session.cluster.metadata.keyspaces[store.keyspace]
    assert names <= set(ks_meta.tables)
