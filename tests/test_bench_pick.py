"""The autotune pick policy (bench.autotune_parity / autotune_pick) is
decision-gated: a Pallas config that flips any pixel's structural
decisions vs the XLA baseline is demoted regardless of speed
(docs/DIVERGENCE.md #1 mega row; VERDICT r3 #3 enforcement side).

Pure-function tests — the TPU-only autotune block in bench.measure
composes exactly these, so the policy is provable without hardware.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import autotune_parity, autotune_pick  # noqa: E402


def _outs(n, meta):
    return np.asarray(n), np.asarray(meta)


def _probe(n_pixels=4, flip_day=None, flip_nseg=None, jitter_chprob=False):
    """Baseline-shaped probe output [1, P] / [1, P, 2, 6] with optional
    single-pixel decision flips or a float-only chprob jitter."""
    n = np.full((1, n_pixels), 2, np.int32)
    meta = np.tile(np.arange(12, dtype=np.float32).reshape(1, 1, 2, 6),
                   (1, n_pixels, 1, 1))
    if flip_nseg is not None:
        n = n.copy()
        n[0, flip_nseg] = 1
    if flip_day is not None:
        meta = meta.copy()
        meta[0, flip_day, 0, 2] += 1.0          # bday column
    if jitter_chprob:
        meta = meta.copy()
        meta[..., 3] += 1e-5                    # col 3 is NOT decision-gated
    return _outs(n, meta)


def test_parity_exact_and_flips():
    outs = {"0": _probe(), "mega": _probe(),
            "score": _probe(flip_day=1),
            "fit": _probe(flip_nseg=2),
            "monitor": _probe(jitter_chprob=True)}
    parity, exact = autotune_parity(outs)
    assert exact == {"mega": True, "score": False, "fit": False,
                     "monitor": True}
    assert parity["score"]["decision_agree"] == 0.75
    assert parity["fit"]["nseg_agree"] == 0.75
    # chprob jitter is invisible to the decision gate but visible to the
    # 2e-4 meta envelope only if it exceeds atol (1e-5 doesn't)
    assert parity["monitor"]["decision_agree"] == 1.0
    assert parity["monitor"]["meta_agree"] == 1.0


def test_single_pixel_flip_gates_even_when_fraction_rounds_to_one():
    """The gate must use the exact predicate: one flipped pixel in 20001
    rounds to decision_agree == 1.0 but still demotes."""
    outs = {"0": _probe(n_pixels=20001), "mega": _probe(n_pixels=20001,
                                                       flip_day=7)}
    parity, exact = autotune_parity(outs)
    assert parity["mega"]["decision_agree"] == 1.0   # display rounds up
    assert exact["mega"] is False                    # gate does not
    pick, demoted, unavailable = autotune_pick(
        {"0": 1.0, "mega": 9.9}, {}, exact)
    assert pick == "0" and demoted == ["mega"] and not unavailable


def test_fastest_clean_config_wins():
    exact = {"mega": True, "score": True, "fit": False}
    pick, demoted, unavailable = autotune_pick(
        {"0": 1.0, "mega": 3.0, "score": 2.0, "fit": 5.0}, {}, exact)
    assert pick == "mega"
    assert demoted == ["fit"]
    assert not unavailable


def test_errored_config_excluded_but_not_demoted():
    # 'tmask' errored: rate 0.0, no parity entry -> neither picked nor
    # listed as a decision divergence (it never produced decisions).
    exact = {"mega": True}
    pick, demoted, _ = autotune_pick(
        {"0": 1.0, "mega": 2.0, "tmask": 0.0},
        {"tmask": "RuntimeError('Mosaic')"}, exact)
    assert pick == "mega"
    assert demoted == []


def test_baseline_error_falls_back_to_fastest_measured():
    # '0' probe errored -> no parity evidence at all; the fastest config
    # that actually ran wins and the artifact says parity_unavailable.
    pick, demoted, unavailable = autotune_pick(
        {"0": 0.0, "mega": 2.0, "score": 1.0},
        {"0": "RuntimeError('tunnel hiccup')"}, {})
    assert pick == "mega"
    assert demoted == []
    assert unavailable


def test_baseline_ok_all_others_errored_is_not_parity_unavailable():
    # The baseline ran and wins by default; the evidence gap is fully
    # described by the errors dict, so parity_unavailable must NOT be
    # set (it is reserved for 'the baseline probe itself errored').
    pick, demoted, unavailable = autotune_pick(
        {"0": 1.0, "mega": 0.0, "score": 0.0},
        {"mega": "RuntimeError", "score": "RuntimeError"}, {})
    assert pick == "0"
    assert demoted == []
    assert not unavailable


def test_everything_errored_still_returns_a_pick():
    pick, _, _ = autotune_pick(
        {"0": 0.0}, {"0": "RuntimeError"}, {})
    assert pick == "0"


# ---------------------------------------------------------------------------
# Artifact scrubbing (ISSUE 3 satellite): ANSI escapes stripped, error
# text truncated, before the bench JSON line becomes a round artifact.
# ---------------------------------------------------------------------------

from bench import ERR_TEXT_LIMIT, clean_text, scrub_artifact  # noqa: E402


def test_clean_text_strips_raw_and_repr_escaped_ansi():
    raw = "\x1b[32m INFO\x1b[0m compiling"
    assert clean_text(raw) == " INFO compiling"
    # repr() of a string holding ESC bytes yields literal "\x1b[2m" text —
    # the form BENCH_r05.json actually embedded.
    escaped = r"JaxRuntimeError('\x1b[2m2026-08-02\x1b[0m \x1b[33mWARN\x1b[0m boom')"
    assert "\\x1b[" not in clean_text(escaped)
    assert "boom" in clean_text(escaped)


def test_clean_text_truncates_with_marker():
    s = "e" * 1000
    out = clean_text(s, limit=100)
    assert out.startswith("e" * 100)
    assert out.endswith("...[+900 chars]")
    assert clean_text("short", limit=100) == "short"


def test_scrub_artifact_truncates_error_fields_only():
    rec = {
        "value": 1.5,
        "detail": {
            "note": "n" * 2000,                      # not an error key
            "pallas_autotune": {
                "errors": {"mega": "\x1b[31m" + "x" * 5000 + "\x1b[0m"},
            },
            "last_tpu_capture": {"tail": "t" * 5000},
            "nested": ["\x1b[2mdim\x1b[0m", 3],
        },
    }
    out = scrub_artifact(rec)
    assert out["value"] == 1.5
    err = out["detail"]["pallas_autotune"]["errors"]["mega"]
    assert len(err) < ERR_TEXT_LIMIT + 40 and "\x1b" not in err
    assert len(out["detail"]["last_tpu_capture"]["tail"]) < ERR_TEXT_LIMIT + 40
    assert out["detail"]["note"] == "n" * 2000       # non-error text intact
    assert out["detail"]["nested"][0] == "dim"
    assert out["detail"]["nested"][1] == 3


def test_clean_text_strips_doubly_escaped_ansi():
    """BENCH_r05's actual failure mode: the error text passed through
    repr() twice (error -> errors dict -> harness log tail), so the ESC
    byte appears as literal backslash-backslash-x1b — the old
    single-backslash alternation missed it and kilobytes of axon
    terminal log survived into the artifact."""
    once = r"\x1b[2m2026-08-02\x1b[0m WARN boom"
    twice = once.replace("\\", "\\\\")
    thrice = twice.replace("\\", "\\\\")
    for s in (once, twice, thrice):
        out = clean_text(s)
        assert "x1b[" not in out and "boom" in out, s


# ---------------------------------------------------------------------------
# tunnel_health (ISSUE 6 satellite): the bench artifact carries a
# structured probe diagnosis instead of a raw ANSI log tail.
# ---------------------------------------------------------------------------

from bench import probe_accelerator  # noqa: E402


def _fake_probe_run(monkeypatch, rc, stdout, stderr=""):
    import subprocess as sp

    import bench as bench_mod

    monkeypatch.setattr(
        bench_mod.subprocess, "run",
        lambda *a, **kw: sp.CompletedProcess(a, rc, stdout, stderr))


def test_probe_accelerator_structured_health_on_cpu_host(monkeypatch):
    """On a CPU-only host the probe reaches the cpu backend and says so
    (rc 1, ok False, a human-readable reason) — the block BENCH_r06's
    artifact embeds as detail.tunnel_health.  The probe subprocess is
    faked (a real jax-import child costs seconds per tier-1 run and
    hangs with the tunnel — the exact condition the probe guards);
    test_probe_accelerator_live is the real-probe integration rung."""
    _fake_probe_run(monkeypatch, 1, "PROBE_PLATFORM cpu\n")
    h = probe_accelerator(timeout=5.0, retries=0)
    assert h == {"ok": False, "rc": 1, "backend": "cpu",
                 "reason": "cpu-only backend (no accelerator visible)",
                 "attempts": [
                     {"ok": False, "rc": 1, "backend": "cpu",
                      "reason": "cpu-only backend (no accelerator "
                                "visible)"}]}


def test_probe_accelerator_crash_reason_is_ansi_stripped(monkeypatch):
    """A crashed probe reports its last stderr line with escape codes
    stripped — never an empty or ANSI-laden diagnosis."""
    _fake_probe_run(monkeypatch, 134, "",
                    "boot log line\n\x1b[31mSIGABRT in \\x1b[2mpjrt\n")
    h = probe_accelerator(timeout=5.0, retries=0)
    assert h["ok"] is False and h["rc"] == 134 and h["backend"] is None
    assert h["reason"]
    assert "\x1b" not in h["reason"] and "x1b" not in h["reason"]
    assert "SIGABRT" in h["reason"]


def test_probe_accelerator_ok_path(monkeypatch):
    _fake_probe_run(monkeypatch, 0,
                    "PROBE_PLATFORM tpu\nPROBE_OK tpu\n")
    h = probe_accelerator(timeout=5.0)
    assert h == {"ok": True, "rc": 0, "backend": "tpu", "reason": "ok",
                 "attempts": [{"ok": True, "rc": 0, "backend": "tpu",
                               "reason": "ok"}]}


def test_probe_accelerator_retries_flaky_tunnel(monkeypatch):
    """A hung first attempt followed by a healthy one must NOT declare a
    CPU fallback: the probe retries with backoff (injectable sleep) and
    the attempt history records the flake — the BENCH_r05 satellite."""
    import subprocess as sp

    import bench as bench_mod

    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise sp.TimeoutExpired(cmd="probe", timeout=5)
        return sp.CompletedProcess(a, 0, "PROBE_PLATFORM tpu\nPROBE_OK tpu\n")

    monkeypatch.setattr(bench_mod.subprocess, "run", flaky)
    slept = []
    h = probe_accelerator(timeout=5.0, retries=2, sleep=slept.append)
    assert h["ok"] is True and h["backend"] == "tpu"
    assert len(h["attempts"]) == 2
    assert h["attempts"][0]["ok"] is False
    assert "timeout" in h["attempts"][0]["reason"]
    assert h["attempts"][1]["ok"] is True
    assert slept and all(s > 0 for s in slept)


def test_probe_accelerator_exhausted_retries_report_last_failure(
        monkeypatch):
    """Every attempt failing declares the fallback with the LAST failure
    as the verdict and the full history in ``attempts``."""
    _fake_probe_run(monkeypatch, 1, "PROBE_PLATFORM cpu\n")
    h = probe_accelerator(timeout=5.0, retries=2, sleep=lambda s: None)
    assert h["ok"] is False and h["backend"] == "cpu"
    assert len(h["attempts"]) == 3
    assert all(not a["ok"] for a in h["attempts"])


@pytest.mark.slow  # real python -c child imports jax (seconds; hangs with the tunnel down until the probe timeout)
def test_probe_accelerator_live():
    h = probe_accelerator(timeout=240.0, retries=0)
    assert set(h) >= {"ok", "rc", "backend", "reason"}
    assert isinstance(h["ok"], bool)
    if not h["ok"]:
        assert h["reason"]
        assert "\x1b" not in h["reason"]
    if h["backend"] == "cpu":
        assert h["ok"] is False and h["rc"] == 1
        assert "cpu" in h["reason"]


def test_probe_timeout_reports_hung_tunnel(monkeypatch):
    import subprocess as sp

    import bench as bench_mod

    def hang(*a, **kw):
        raise sp.TimeoutExpired(cmd="probe", timeout=kw.get("timeout", 1))

    monkeypatch.setattr(bench_mod.subprocess, "run", hang)
    h = bench_mod.probe_accelerator(timeout=5.0, retries=0)
    assert h == {"ok": False, "rc": None, "backend": None,
                 "reason": "timeout after 5s (tunnel hung)",
                 "attempts": [{"ok": False, "rc": None, "backend": None,
                               "reason": "timeout after 5s (tunnel hung)"}]}


def test_repro_block_seeds_parsing(tmp_path, monkeypatch):
    """The fuse_repro.json -> block-seed contract: absent file and
    unreachable-Mosaic artifacts yield no seeds; a reachable artifact
    yields only the pairings whose ladder actually found a compiling
    block (null smallest_ok_block rows drop out)."""
    import json as _json

    from bench import repro_block_seeds

    monkeypatch.setenv("FIREBIRD_FUSE_DIR", str(tmp_path))
    assert repro_block_seeds() == {}                  # no artifact yet
    art = tmp_path / "fuse_repro.json"
    art.write_text(_json.dumps({
        "mosaic_reachable": False,
        "probes": {"mega": {"smallest_ok_block": 256}}}))
    assert repro_block_seeds() == {}                  # advisory-only host
    art.write_text(_json.dumps({
        "mosaic_reachable": True,
        "probes": {"mega": {"smallest_ok_block": 256},
                   "mon+mixed": {"smallest_ok_block": 128},
                   "fused": {"smallest_ok_block": None}}}))
    assert repro_block_seeds() == {"mega": 256, "mon+mixed": 128}
    art.write_text("not json")
    assert repro_block_seeds() == {}                  # corrupt artifact


def test_apply_tune_flag_env_grammar():
    """Every rung shape the autotune races maps to exactly one env
    combination (FIREBIRD_FUSED_FIT tier, FIREBIRD_PALLAS components,
    FIREBIRD_MIXED_PRECISION, FIREBIRD_MEGA_BLOCK_P seed)."""
    from bench import apply_tune_flag

    # apply_tune_flag writes os.environ directly, and monkeypatch.delenv
    # on an ABSENT key registers no undo — snapshot/restore by hand or
    # the last case's fused/mixed env leaks into the whole suite.
    keys = ("FIREBIRD_FUSED_FIT", "FIREBIRD_PALLAS",
            "FIREBIRD_MIXED_PRECISION", "FIREBIRD_MEGA_BLOCK_P")
    saved = {k: os.environ.get(k) for k in keys}
    seeds = {"mega": 256, "mega+mixed": 384, "mon": 128,
             "mon+mixed": 512, "fused": 640}
    cases = {
        # flag -> (FUSED_FIT, PALLAS, MIXED, BLOCK_P)
        "0": ("0", "0", "0", "0"),
        "fit,init": ("0", "fit,init", "0", "0"),
        "mega": ("0", "mega", "0", "256"),
        "mega+mixed": ("0", "mega", "1", "384"),
        "mixed": ("0", "0", "1", "0"),
        "fused": ("1", "0", "0", "640"),
        "fused+fit,init": ("1", "fit,init", "0", "640"),
        "fused+fit,init+mixed": ("1", "fit,init", "1", "0"),
        "mon": ("mon", "0", "0", "128"),
        "mon+fit": ("mon", "fit", "0", "128"),
        "mon+fit+mixed": ("mon", "fit", "1", "512"),
    }
    try:
        for flag, (ff, pal, mx, bp) in cases.items():
            apply_tune_flag(flag, seeds)
            got = tuple(os.environ[k] for k in keys)
            assert got == (ff, pal, mx, bp), flag
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
