"""Fault injection, retry policy, circuit breaker, quarantine, and the
run manifest (firebird_tpu/faults.py, retry.py, driver/quarantine.py) —
plus the end-to-end per-chip isolation contract: one poisoned chip costs
one chip, never its chunk, and --resume drains the quarantine."""

import json
import os

import pytest

from firebird_tpu import faults as faultlib
from firebird_tpu import retry as retrylib
from firebird_tpu.config import Config
from firebird_tpu.driver import core
from firebird_tpu.driver import quarantine as qlib
from firebird_tpu.ingest import SyntheticSource
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.store import MemoryStore

ACQ = "1995-01-01/1997-06-01"    # matches test_driver: shared jit cache
CFG = Config(store_backend="memory", source_backend="synthetic",
             chips_per_batch=1, dtype="float64", device_sharding="off",
             fetch_retries=0)


def good_source():
    return SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                           cloud_frac=0.1)


# ---------------------------------------------------------------------------
# Fault plan parsing
# ---------------------------------------------------------------------------

def test_plan_parse_and_empty():
    assert faultlib.FaultPlan.parse("") is None
    assert faultlib.FaultPlan.parse(None) is None
    assert faultlib.FaultPlan.parse("  ; ") is None
    plan = faultlib.FaultPlan.parse(
        "ingest:p=0.05,timeout,seed=7;store:after=40,brownout=3")
    assert plan.injector("ingest").spec.p == 0.05
    assert plan.injector("ingest").spec.kind == "timeout"
    assert plan.injector("store").spec.after == 40
    assert plan.injector("store").spec.brownout == 3
    assert plan.injector("writer") is None


@pytest.mark.parametrize("bad", [
    "nonsense",                      # no colon
    "bogus:p=0.5",                   # unknown target
    "ingest:p=2.0",                  # p out of range
    "ingest:wat=1",                  # unknown key
    "ingest:p=abc",                  # unparseable value
    "ingest:frobnicate",             # unknown flag
    "ingest:seed=7",                 # scope that injects nothing
    "ingest:p=0.5;ingest:p=0.1",     # duplicate scope
    "store:chip=1:2",                # chip= is meaningless off ingest/aux
])
def test_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        faultlib.FaultPlan.parse(bad)


def test_config_validates_fault_plan_and_knobs():
    with pytest.raises(ValueError):
        Config(faults="ingest:p=2.0")
    with pytest.raises(ValueError):
        Config(http_timeout=0)
    with pytest.raises(ValueError):
        Config(retry_budget=-1)
    with pytest.raises(ValueError):
        Config(breaker_threshold=2, breaker_cooldown_sec=0)
    env = {"FIREBIRD_FAULTS": "ingest:p=0.5", "FIREBIRD_HTTP_TIMEOUT": "5",
           "FIREBIRD_RETRY_BUDGET": "9", "FIREBIRD_BREAKER_THRESHOLD": "2",
           "FIREBIRD_BREAKER_COOLDOWN": "1.5"}
    cfg = Config.from_env(env=env)
    assert (cfg.faults, cfg.http_timeout, cfg.retry_budget,
            cfg.breaker_threshold, cfg.breaker_cooldown_sec) == \
        ("ingest:p=0.5", 5.0, 9, 2, 1.5)


# ---------------------------------------------------------------------------
# Injector schedules
# ---------------------------------------------------------------------------

def _decisions(inj, n, chip=None):
    out = []
    for _ in range(n):
        try:
            inj.fire(chip=chip)
            out.append(False)
        except Exception:
            out.append(True)
    return out


def test_injector_probability_and_determinism():
    mk = lambda: faultlib.FaultInjector(
        faultlib.FaultSpec("ingest", p=0.3, seed=42))
    a, b = _decisions(mk(), 200), _decisions(mk(), 200)
    assert a == b                         # seeded: replays identically
    assert 20 < sum(a) < 120              # roughly p=0.3
    always = faultlib.FaultInjector(faultlib.FaultSpec("ingest", p=1.0))
    assert _decisions(always, 5) == [True] * 5


def test_injector_brownout_window_is_one_shot():
    inj = faultlib.FaultInjector(
        faultlib.FaultSpec("store", after=3, brownout=2))
    # ops 1-3 fine, 4-5 fail, 6+ healed forever
    assert _decisions(inj, 8) == [False, False, False, True, True,
                                  False, False, False]


def test_injector_chip_poison_and_error_kinds():
    inj = faultlib.FaultInjector(
        faultlib.FaultSpec("ingest", chips=frozenset({(5, 7)}),
                           kind="timeout"))
    with pytest.raises(TimeoutError):
        inj.fire(chip=(5, 7))
    inj.fire(chip=(5, 8))                 # other chips pass
    conn = faultlib.FaultInjector(
        faultlib.FaultSpec("ingest", p=1.0, kind="conn"))
    with pytest.raises(ConnectionError):
        conn.fire()
    io = faultlib.FaultInjector(faultlib.FaultSpec("ingest", p=1.0))
    with pytest.raises(OSError):
        io.fire()


def test_injection_counters():
    obs_metrics.reset_registry()
    inj = faultlib.FaultInjector(faultlib.FaultSpec("store", p=1.0))
    for _ in range(3):
        with pytest.raises(OSError):
            inj.fire()
    assert obs_metrics.counter("faults_injected").value == 3
    assert obs_metrics.counter("faults_injected_store").value == 3


def test_wrap_identity_off_the_hot_path():
    """The acceptance bar: with no plan (or no matching scope) the
    wrappers return the SAME object — zero proxies on the hot path."""
    src, store, writer = object(), object(), object()
    assert faultlib.wrap_source(src, None) is src
    assert faultlib.wrap_store(store, None) is store
    assert faultlib.wrap_writer(writer, None) is writer
    plan = faultlib.FaultPlan.parse("store:after=1")
    assert faultlib.wrap_source(src, plan) is src
    assert faultlib.wrap_writer(writer, plan) is writer
    assert isinstance(faultlib.wrap_store(store, plan),
                      faultlib.FaultyStore)


def test_aux_only_plan_still_wraps_the_source():
    """Regression: a plan with ONLY an aux scope must still proxy the
    source — otherwise the chaos drill the operator asked for silently
    tests nothing."""
    plan = faultlib.FaultPlan.parse("aux:p=1.0")
    src = faultlib.wrap_source(good_source(), plan)
    assert isinstance(src, faultlib.FaultySource)
    assert src.chip(100, 200, ACQ).cx == 100   # chip path uninjected
    with pytest.raises(OSError):
        src.aux(100, 200)


def test_faulty_source_proxies_and_passes_through():
    plan = faultlib.FaultPlan.parse("ingest:chip=100:200")
    src = faultlib.wrap_source(good_source(), plan)
    assert src.seed == 9                  # __getattr__ passthrough
    with pytest.raises(OSError):
        src.chip(100, 200, ACQ)
    chip = src.chip(3100, 200, ACQ)       # unpoisoned chips flow through
    assert chip.cx == 3100


# ---------------------------------------------------------------------------
# Retry policy
# ---------------------------------------------------------------------------

class _Log:
    def __init__(self):
        self.lines = []

    def warning(self, fmt, *a):
        self.lines.append(fmt % a)

    error = warning
    info = warning


def test_retry_policy_jitter_bounds_and_injected_sleep():
    obs_metrics.reset_registry()
    delays = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise IOError("blip")
        return "ok"

    pol = retrylib.RetryPolicy(5, base=1.0, cap=30.0,
                               sleep=delays.append)
    assert pol.run(_Log(), "op", flaky) == "ok"
    assert len(delays) == 3               # three failures, three sleeps
    # decorrelated jitter: bounded by [base, cap], and bounded by 3x the
    # previous delay
    prev = 1.0
    for d in delays:
        assert 1.0 <= d <= min(30.0, 3 * max(prev, 1.0) + 1e-9)
        prev = d
    assert obs_metrics.counter("fetch_retries").value == 3
    # satellite: the counter carries a help string now
    assert obs_metrics.counter("fetch_retries").help


def test_retry_policy_exhausts_and_raises():
    pol = retrylib.RetryPolicy(2, sleep=lambda s: None)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise IOError("down")

    with pytest.raises(IOError):
        pol.run(_Log(), "op", always)
    assert calls["n"] == 3                # 1 try + 2 retries


def test_retry_budget_is_shared_and_fails_fast():
    budget = retrylib.RetryBudget(2)
    pol = retrylib.RetryPolicy(10, budget=budget, sleep=lambda s: None)
    log = _Log()

    def always():
        raise IOError("down")

    with pytest.raises(IOError):
        pol.run(log, "op", always)
    # 10 retries allowed per-op, but the run budget capped it at 2
    assert budget.remaining() == 0
    assert any("budget is exhausted" in ln for ln in log.lines)
    # a second policy sharing the budget gets no retries at all
    calls = {"n": 0}
    pol2 = retrylib.RetryPolicy(10, budget=budget, sleep=lambda s: None)

    def count():
        calls["n"] += 1
        raise IOError("down")

    with pytest.raises(IOError):
        pol2.run(log, "op", count)
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_half_opens_and_closes():
    obs_metrics.reset_registry()
    clk = _Clock()
    br = retrylib.CircuitBreaker(3, cooldown_sec=10.0, clock=clk)
    assert br.state_name() == "closed"
    for _ in range(3):
        br.record_failure()
    assert br.state_name() == "open"
    assert obs_metrics.counter("breaker_open_total").value == 1
    assert obs_metrics.gauge("breaker_state").value == retrylib.OPEN

    # acquire blocks while open; the injected sleep advances the clock
    waits = []

    def sleep(s):
        waits.append(s)
        clk.t += s

    br.acquire(sleep)                     # returns once cooldown elapsed
    assert waits and sum(waits) >= 10.0
    assert br.state_name() == "half_open"
    # a second caller must NOT get through while the probe is in flight
    ok, _ = br._try_enter()
    assert not ok
    br.record_success()                   # probe wins: circuit closes
    assert br.state_name() == "closed"
    assert obs_metrics.gauge("breaker_state").value == retrylib.CLOSED


def test_breaker_reopens_on_failed_probe():
    clk = _Clock()
    br = retrylib.CircuitBreaker(2, cooldown_sec=5.0, clock=clk)
    br.record_failure()
    br.record_failure()
    clk.t += 6.0
    ok, _ = br._try_enter()               # the half-open probe
    assert ok
    br.record_failure()                   # probe loses: open again
    assert br.state_name() == "open"
    ok, _ = br._try_enter()
    assert not ok                         # fresh cooldown applies


def test_breaker_ignores_stragglers():
    """Only the half-open probe's own outcome may transition a non-closed
    circuit: a straggler request admitted back when the circuit was still
    closed must neither close an open breaker on success nor free the
    probe slot on failure."""
    import threading

    clk = _Clock()
    br = retrylib.CircuitBreaker(2, cooldown_sec=5.0, clock=clk)
    br.record_failure()
    br.record_failure()
    assert br.state_name() == "open"
    br.record_success()                   # straggler success while open
    assert br.state_name() == "open"      # proves nothing about NOW
    clk.t += 6.0
    ok, _ = br._try_enter()
    assert ok                             # this thread is the probe
    res = {}

    def straggler():
        br.record_failure()               # straggler failure mid-probe
        res["enter"] = br._try_enter()[0]

    t = threading.Thread(target=straggler)
    t.start()
    t.join()
    assert br.state_name() == "half_open"  # probe slot NOT freed
    assert res["enter"] is False
    br.record_success()                   # the probe's outcome decides
    assert br.state_name() == "closed"


def test_make_breaker_from_config():
    assert retrylib.make_breaker(Config(breaker_threshold=0)) is None
    br = retrylib.make_breaker(Config(breaker_threshold=4,
                                      breaker_cooldown_sec=7.0))
    assert (br.threshold, br.cooldown_sec) == (4, 7.0)


# ---------------------------------------------------------------------------
# Quarantine + run manifest
# ---------------------------------------------------------------------------

def test_quarantine_roundtrip_and_history(tmp_path):
    path = str(tmp_path / "quarantine.json")
    q = qlib.Quarantine(path, run_id="run-1")
    q.record((3, 4), IOError("chipmunk down"), attempts=4)
    q.record((3, 4), TimeoutError("still down"), attempts=4)
    q.record((5, 6), IOError("other"), attempts=1, stage="chunk")
    assert len(q) == 2 and q.chip_ids() == {(3, 4), (5, 6)}

    q2 = qlib.Quarantine.load(path, run_id="run-2")
    doc = q2.snapshot()["chips"]
    e = doc["3,4"]
    assert e["error"] == "TimeoutError"        # latest error class
    assert len(e["history"]) == 2              # full attempt history
    assert doc["5,6"]["stage"] == "chunk"
    assert q2.discard((3, 4)) and not q2.discard((9, 9))
    assert qlib.Quarantine.load(path).chip_ids() == {(5, 6)}
    assert q2.discard_many([(5, 6), (7, 7)]) == 1
    assert len(qlib.Quarantine.load(path)) == 0


def test_quarantine_memory_backend_stays_in_memory():
    assert qlib.quarantine_path(Config(store_backend="memory")) is None
    q = qlib.Quarantine(None)
    q.record((1, 2), IOError("x"), attempts=1)
    assert len(q) == 1                    # ledger works without a file


def test_manifest_refuses_mismatched_acquired(tmp_path):
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"))
    assert qlib.write_manifest(cfg, acquired=ACQ, run_id="r1",
                               tile={"h": 20, "v": 11})
    log = _Log()
    qlib.check_resume(cfg, acquired=ACQ, log=log)       # match: silent ok
    with pytest.raises(qlib.ResumeMismatch):
        qlib.check_resume(cfg, acquired="2001-01-01/2002-01-01", log=log)
    # changed RESULT-affecting config: warn, not refuse
    cfg2 = Config(store_backend="sqlite",
                  store_path=str(tmp_path / "fb.db"), max_obs=128)
    qlib.check_resume(cfg2, acquired=ACQ, log=log)
    assert any("fingerprint" in ln for ln in log.lines)


def test_manifest_missing_warns_and_proceeds(tmp_path):
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"))
    log = _Log()
    qlib.check_resume(cfg, acquired=ACQ, log=log)
    assert any("no run manifest" in ln for ln in log.lines)


def test_truncated_manifest_warns_and_proceeds(tmp_path):
    """SIGKILL-mid-write regression: a torn run_manifest.json must not
    block --resume with a JSON parse error — the unreadable-manifest
    path warns and proceeds on the pre-manifest assumption."""
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"))
    path = qlib.write_manifest(cfg, acquired=ACQ, run_id="r1")
    with open(path) as f:
        text = f.read()
    with open(path, "w") as f:
        f.write(text[: len(text) // 2])       # torn half-document
    log = _Log()
    qlib.check_resume(cfg, acquired="2001-01-01/2002-01-01", log=log)
    assert any("unreadable run manifest" in ln for ln in log.lines)


def test_truncated_quarantine_loads_empty_with_warning(tmp_path):
    """Same regression for quarantine.json: a torn dead-letter manifest
    starts empty (warned) instead of crashing the resume that exists to
    drain it."""
    path = str(tmp_path / "quarantine.json")
    q = qlib.Quarantine(path)
    q.record((3, 4), IOError("x"), attempts=1)
    with open(path) as f:
        text = f.read()
    with open(path, "w") as f:
        f.write(text[: len(text) // 2])
    q2 = qlib.Quarantine.load(path)
    assert len(q2) == 0                       # empty, not an exception
    q2.record((5, 6), IOError("y"), attempts=1)   # and usable again
    assert qlib.Quarantine.load(path).chip_ids() == {(5, 6)}


def test_quarantine_concurrent_instances_never_lose_entries(tmp_path):
    """Fleet regression: two workers share one quarantine.json through
    separate Quarantine instances.  Each mutation folds into the
    freshest on-disk state under a file lock, so one worker's record
    cannot erase the other's (whole-file dump = lost update)."""
    path = str(tmp_path / "quarantine.json")
    a = qlib.Quarantine(path, run_id="worker-a")
    b = qlib.Quarantine(path, run_id="worker-b")
    a.record((1, 1), IOError("a's letter"), attempts=1)
    b.record((2, 2), IOError("b's letter"), attempts=1)   # must not wipe (1,1)
    assert qlib.Quarantine.load(path).chip_ids() == {(1, 1), (2, 2)}
    # discard is write-through too: a's discard deletes only its chip
    assert a.discard((1, 1))
    assert qlib.Quarantine.load(path).chip_ids() == {(2, 2)}


def test_atomic_write_json_replaces_and_leaves_no_temp(tmp_path):
    """The shared write-temp -> fsync -> os.replace helper behind both
    manifests: the target is always a complete document and the
    pid-suffixed temp never survives."""
    path = str(tmp_path / "doc.json")
    qlib.atomic_write_json(path, {"v": 1})
    qlib.atomic_write_json(path, {"v": 2})
    assert json.load(open(path)) == {"v": 2}
    assert os.listdir(tmp_path) == ["doc.json"]


# ---------------------------------------------------------------------------
# Degraded ops surface
# ---------------------------------------------------------------------------

def test_healthz_reports_degraded_not_dead():
    import urllib.request

    from firebird_tpu.obs import server as obs_server

    q = qlib.Quarantine(None)
    q.record((1, 2), IOError("poisoned"), attempts=1)
    br = retrylib.CircuitBreaker(2, cooldown_sec=30.0, clock=lambda: 0.0)
    status = obs_server.RunStatus("run-x", "changedetection",
                                  quarantine=q, breaker=br)
    srv = obs_server.start_ops_server(0, status, host="127.0.0.1")
    try:
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
        assert (r.status, r.read()) == (200, b"degraded\n")
        p = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/progress", timeout=5).read())
        assert p["degraded"]["active"] is True
        assert p["degraded"]["chips_quarantined"] == 1
        assert p["degraded"]["breaker"]["state"] == "closed"
        # drained quarantine + closed breaker -> plain ok again
        q.discard((1, 2))
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
        assert (r.status, r.read()) == (200, b"ok\n")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# HTTP timeout knob (satellite)
# ---------------------------------------------------------------------------

def test_chipmunk_timeout_is_configurable():
    from firebird_tpu.ingest.sources import ChipmunkSource

    cfg = Config(source_backend="chipmunk", http_timeout=5.5)
    assert core.make_source(cfg).timeout == 5.5
    assert core.make_aux_source(cfg).timeout == 5.5
    with pytest.raises(ValueError):
        ChipmunkSource("http://x", timeout=0)


# ---------------------------------------------------------------------------
# End to end: poisoned chip -> quarantine -> resume drains
# ---------------------------------------------------------------------------

def test_poisoned_chip_no_longer_fails_its_chunk(tmp_path):
    """The acceptance criterion: one permanently failing chip in a
    2-chip chunk leaves chunk_size-1 chips landed, the poisoned chip in
    quarantine.json, and a resume (after the poison clears) drains the
    quarantine and completes the tile — row counts equal to a clean
    run's."""
    from firebird_tpu import grid
    from firebird_tpu.store import SqliteStore
    from firebird_tpu.utils.fn import take

    cids = list(take(2, grid.chips(grid.tile(x=100, y=200))))
    poisoned = cids[0]
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"),
                 source_backend="synthetic", chips_per_batch=1,
                 dtype="float64", device_sharding="off", fetch_retries=0,
                 faults=f"ingest:chip={poisoned[0]}:{poisoned[1]}")
    done = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                                chunk_size=2, cfg=cfg, source=good_source())
    # chunk_size-1 chips of the poisoned chunk landed
    assert list(done) == [cids[1]]
    store = SqliteStore(cfg.store_path, cfg.keyspace())
    assert store.count("chip") == 1
    qpath = qlib.quarantine_path(cfg)
    doc = json.load(open(qpath))
    key = f"{poisoned[0]},{poisoned[1]}"
    assert doc["chips"][key]["error"] == "InjectedFault"
    assert doc["chips"][key]["history"][0]["attempts"] == 1
    # the run manifest pinned this run's identity
    assert json.load(open(qlib.manifest_path(cfg)))["acquired"] == ACQ

    # resume with the poison cleared: quarantine drains, tile completes
    healed = Config(**{**cfg.__dict__, "faults": ""})
    out = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                               chunk_size=2, cfg=healed,
                               source=good_source(), resume=True)
    assert set(out) == set(cids)
    assert store.count("chip") == 2
    assert len(qlib.Quarantine.load(qpath)) == 0

    # resume against a different acquired range REFUSES
    with pytest.raises(qlib.ResumeMismatch):
        core.changedetection(x=100, y=200,
                             acquired="2001-01-01/2002-06-01", number=2,
                             chunk_size=2, cfg=healed,
                             source=good_source(), resume=True)


def test_transient_injected_faults_cost_retries_not_results(monkeypatch):
    """An ingest fault plan below the retry ceiling is absorbed entirely:
    all chips land, faults_injected and fetch_retries both moved."""
    monkeypatch.setattr(core.time, "sleep", lambda s: None)
    cfg = Config(store_backend="memory", source_backend="synthetic",
                 chips_per_batch=1, dtype="float64",
                 device_sharding="off", fetch_retries=3,
                 faults="ingest:p=0.4,seed=3")
    store = MemoryStore("faults")
    done = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                                chunk_size=2, cfg=cfg, source=good_source(),
                                store=store)
    assert len(done) == 2
    assert store.count("chip") == 2
    # the report registry was reset by the run; read the live registry
    assert obs_metrics.counter("faults_injected").value > 0
    assert obs_metrics.counter("fetch_retries").value > 0
