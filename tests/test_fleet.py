"""Fleet work queue: lease/heartbeat/fence scheduling, deterministically.

Every lease-protocol test drives an injectable clock — no sleeps: lease
expiry, zombie fencing, dead-lettering, and dependency gating are all
exact clock arithmetic.  The end-to-end test proves the headline
contract at small scale: a plan drained through fleet workers produces a
store row-identical to the direct single-process driver run.
"""

import json
import os
import threading

import pytest

from firebird_tpu.config import Config
from firebird_tpu.fleet import (FencedStore, FleetQueue, FleetWorker,
                                LeaseLost, StaleFence, enqueue_tile_plan,
                                queue_path)
from firebird_tpu.obs import metrics as obs_metrics

# Matches test_driver/test_faults: the 1-chip f64 kernel shape is
# already jit-cached by the time this file runs in a full-suite pass.
ACQ = "1995-01-01/1997-06-01"


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def clock():
    return Clock()


@pytest.fixture
def queue(tmp_path, clock):
    q = FleetQueue(str(tmp_path / "fleet.db"), lease_sec=30.0, clock=clock)
    yield q
    q.close()


@pytest.fixture(autouse=True)
def _fresh_registry():
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


# ---------------------------------------------------------------------------
# Queue protocol
# ---------------------------------------------------------------------------

def test_enqueue_claim_ack_roundtrip(queue):
    a = queue.enqueue("detect", {"cids": [[1, 2]]})
    b = queue.enqueue("detect", {"cids": [[3, 4]]})
    lease = queue.claim("w1")
    assert lease.job_id == a and lease.payload == {"cids": [[1, 2]]}
    assert lease.fence == 1 and lease.attempts == 1
    lease2 = queue.claim("w2")
    assert lease2.job_id == b and lease2.fence == 2   # monotonic tokens
    queue.ack(lease)
    queue.ack(lease2)
    assert queue.claim("w1") is None
    assert queue.counts() == {"pending": 0, "leased": 0, "done": 2,
                              "dead": 0}
    assert queue.drained()
    hist = [h["event"] for h in queue.job(a)["history"]]
    assert hist == ["enqueued", "claimed", "acked"]


def test_enqueue_validates(queue):
    with pytest.raises(ValueError, match="job_type"):
        queue.enqueue("mine-bitcoin", {})
    with pytest.raises(ValueError, match="max_attempts"):
        queue.enqueue("detect", {}, max_attempts=0)
    with pytest.raises(ValueError, match="unknown job ids"):
        queue.enqueue("detect", {}, depends_on=[999])


def test_dependencies_gate_claims(queue):
    d1 = queue.enqueue("detect", {"n": 1})
    d2 = queue.enqueue("detect", {"n": 2})
    c = queue.enqueue("classify", {}, depends_on=[d1, d2])
    assert queue.job(c)["depends_on"] == [d1, d2]
    l1 = queue.claim("w")
    l2 = queue.claim("w")
    assert {l1.job_id, l2.job_id} == {d1, d2}
    assert queue.claim("w") is None          # classify still blocked
    assert queue.status()["blocked"] == 1
    queue.ack(l1)
    assert queue.claim("w") is None          # one dep is not all deps
    queue.ack(l2)
    lc = queue.claim("w")                    # unblocks on the LAST ack
    assert lc is not None and lc.job_id == c


def test_lease_expiry_requeues_with_history(queue, clock):
    jid = queue.enqueue("detect", {"n": 1}, max_attempts=5)
    stale = queue.claim("w1")
    clock.advance(31.0)                       # past lease_sec=30
    fresh = queue.claim("w2")                 # re-delivery
    assert fresh.job_id == jid
    assert fresh.fence == stale.fence + 1 and fresh.attempts == 2
    events = [h["event"] for h in queue.job(jid)["history"]]
    assert events == ["enqueued", "claimed", "lease_expired", "claimed"]
    # the zombie's protocol ops all reject
    with pytest.raises(LeaseLost):
        queue.heartbeat(stale)
    with pytest.raises(StaleFence):
        queue.ack(stale)
    with pytest.raises(StaleFence):
        queue.fail(stale, RuntimeError("late report"))
    assert queue.fence_rejects() == 3
    # the successor is untouched by the zombie's noise
    queue.heartbeat(fresh)
    queue.ack(fresh)
    assert queue.job(jid)["state"] == "done"
    assert obs_metrics.counter("fleet_jobs_requeued").value == 1


def test_heartbeat_extends_lease(queue, clock):
    queue.enqueue("detect", {})
    lease = queue.claim("w")
    clock.advance(20.0)
    queue.heartbeat(lease)                    # extends to t+20+30
    clock.advance(20.0)                       # t+40 < t+50: still live
    assert queue.fence_valid(lease.job_id, lease.fence)
    queue.ack(lease)
    assert queue.job(lease.job_id)["state"] == "done"


def test_expired_unreclaimed_lease_is_invalid(queue, clock):
    """Fencing is symmetric: once the lease lapses, writes AND ack both
    reject even before anyone re-claims — a zombie can never slip output
    in during the gap between expiry and re-delivery."""
    queue.enqueue("detect", {})
    lease = queue.claim("w")
    clock.advance(31.0)
    assert not queue.fence_valid(lease.job_id, lease.fence)
    with pytest.raises(StaleFence):
        queue.ack(lease)


class _RecordingStore:
    def __init__(self):
        self.writes = []

    def write(self, table, frame):
        self.writes.append((table, frame))
        return 1

    def chip_ids(self, table="segment"):
        return set()


def test_zombie_write_fencing(queue, clock):
    jid = queue.enqueue("detect", {})
    stale = queue.claim("w1")
    inner = _RecordingStore()
    fenced = FencedStore(inner, queue, stale)
    fenced.write("chip", {"cx": [1]})         # live lease: passes through
    assert len(inner.writes) == 1
    clock.advance(31.0)
    fresh = queue.claim("w2")                 # successor owns the job now
    with pytest.raises(StaleFence):
        fenced.write("chip", {"cx": [1]})
    assert len(inner.writes) == 1             # zero stale writes accepted
    assert queue.fence_rejects("write") == 1
    assert queue.fence_rejects() == 1
    # reads pass through untouched (fencing is write-side only)
    assert fenced.chip_ids() == set()
    succ = FencedStore(inner, queue, fresh)
    succ.write("chip", {"cx": [1]})
    assert len(inner.writes) == 2
    queue.ack(fresh)
    assert queue.job(jid)["state"] == "done"


def test_fail_requeues_then_dead_letters(queue, clock):
    jid = queue.enqueue("detect", {}, max_attempts=2)
    lease = queue.claim("w")
    assert queue.fail(lease, RuntimeError("boom")) == "pending"
    lease = queue.claim("w")
    assert lease.attempts == 2
    assert queue.fail(lease, ValueError("worse")) == "dead"
    assert queue.claim("w") is None
    st = queue.status()
    assert st["jobs"]["dead"] == 1
    assert st["dead_errors"] == {"ValueError": 1}
    assert st["dead"][0]["job"] == jid and st["dead"][0]["attempts"] == 2
    # operator revival: fresh attempt budget, claimable again
    assert queue.requeue(jid) == 1
    lease = queue.claim("w")
    assert lease is not None and lease.attempts == 1
    queue.ack(lease)


def test_expired_lease_crashloop_dead_letters(queue, clock):
    """A payload that kills its worker every time must not wedge the
    fleet: the attempt budget counts expired leases too."""
    queue.enqueue("detect", {}, max_attempts=1)
    queue.claim("w1")
    clock.advance(31.0)
    assert queue.claim("w2") is None          # dead-lettered, not re-leased
    st = queue.status()
    assert st["jobs"]["dead"] == 1
    assert st["dead_errors"] == {"LeaseExpired": 1}
    # an expiry that dead-letters was never RE-delivered: the requeue
    # counter must not move (only the dead counter does)
    assert obs_metrics.counter("fleet_jobs_requeued").value == 0
    assert obs_metrics.counter("fleet_jobs_dead").value == 1


def test_wedged_is_one_atomic_snapshot(queue, clock):
    """wedged() verdicts: blocked-behind-dead with nothing leased is
    wedged; blocked behind a LIVE lease or claimable work is not."""
    assert not queue.wedged()                 # empty queue: just drained
    d = queue.enqueue("detect", {}, max_attempts=1)
    c = queue.enqueue("classify", {}, depends_on=[d])
    assert not queue.wedged()                 # d is claimable
    lease = queue.claim("w")
    assert not queue.wedged()                 # d leased: progress possible
    queue.fail(lease, RuntimeError("boom"))   # max_attempts=1 -> dead
    assert queue.wedged()                     # c blocked behind dead d
    queue.requeue(d)
    assert not queue.wedged()                 # revived: claimable again
    assert c


def test_status_lease_view(queue, clock):
    queue.enqueue("detect", {})
    queue.claim("host-a:123")
    clock.advance(10.0)
    st = queue.status()
    (lease,) = st["leases"]
    assert lease["owner"] == "host-a:123"
    assert lease["age_sec"] == 10.0
    assert lease["expires_in_sec"] == 20.0
    assert st["by_type"]["detect"]["leased"] == 1
    assert st["fence_rejects"] == 0 and st["fence_rejects_by_op"] == {}


def test_queue_survives_reopen(tmp_path, clock):
    """The queue IS the durable state: a second FleetQueue over the same
    file (a restarted worker) sees jobs, leases, and the fence seq."""
    path = str(tmp_path / "fleet.db")
    q1 = FleetQueue(path, lease_sec=30.0, clock=clock)
    jid = q1.enqueue("detect", {"n": 1})
    lease = q1.claim("w1")
    q2 = FleetQueue(path, lease_sec=30.0, clock=clock)
    assert q2.job(jid)["state"] == "leased"
    clock.advance(31.0)
    fresh = q2.claim("w2")
    assert fresh.fence == lease.fence + 1     # seq continues across opens
    q2.ack(fresh)
    assert q1.job(jid)["state"] == "done"
    q1.close()
    q2.close()


def test_queue_path_rules(tmp_path):
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"))
    assert queue_path(cfg) == str(tmp_path / "fleet.db")
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "fb.db"),
                 fleet_db=str(tmp_path / "q" / "explicit.db"))
    assert queue_path(cfg) == str(tmp_path / "q" / "explicit.db")
    with pytest.raises(ValueError, match="FIREBIRD_FLEET_DB"):
        queue_path(Config(store_backend="memory"))


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="FLEET_LEASE"):
        Config(fleet_lease_sec=0)
    with pytest.raises(ValueError, match="FLEET_HEARTBEAT"):
        Config(fleet_heartbeat_sec=-1)
    with pytest.raises(ValueError, match="shorter than the lease"):
        Config(fleet_lease_sec=5.0, fleet_heartbeat_sec=5.0)
    with pytest.raises(ValueError, match="FLEET_MAX_ATTEMPTS"):
        Config(fleet_max_attempts=0)
    cfg = Config.from_env(env={"FIREBIRD_FLEET_DB": "/x/q.db",
                               "FIREBIRD_FLEET_LEASE_SEC": "7",
                               "FIREBIRD_FLEET_HEARTBEAT_SEC": "2",
                               "FIREBIRD_FLEET_MAX_ATTEMPTS": "9"})
    assert (cfg.fleet_db, cfg.fleet_lease_sec, cfg.fleet_heartbeat_sec,
            cfg.fleet_max_attempts) == ("/x/q.db", 7.0, 2.0, 9)


def test_stale_fence_is_nonretryable():
    """A fencing rejection must short-circuit the retry policy: no
    backoff sleeps, no budget spend, no breaker strike."""
    from firebird_tpu import retry as retrylib

    sleeps = []
    budget = retrylib.RetryBudget(10)
    breaker = retrylib.CircuitBreaker(1, cooldown_sec=30.0,
                                      clock=lambda: 0.0)
    policy = retrylib.RetryPolicy(3, budget=budget, breaker=breaker,
                                  sleep=sleeps.append)
    calls = []

    def fn():
        calls.append(1)
        raise StaleFence("fenced")

    class _Log:
        def warning(self, *a):
            pass

    with pytest.raises(StaleFence):
        policy.run(_Log(), "op", fn)
    assert len(calls) == 1 and sleeps == []
    assert budget.spent == 0
    assert breaker.state == retrylib.CLOSED


# ---------------------------------------------------------------------------
# Plan builder
# ---------------------------------------------------------------------------

def test_plan_builder_dependencies(queue):
    plan = enqueue_tile_plan(
        queue, [(100.0, 200.0), (150100.0, 200.0)], acquired=ACQ,
        number=4, chunk_size=2, msday=724204, meday=735598,
        products=["seglength"], product_dates=["1996-01-01"])
    assert plan["tiles"] == 2
    assert len(plan["detect"]) == 4           # 2 chunks x 2 tiles
    assert len(plan["classify"]) == 2 and len(plan["product"]) == 2
    assert plan["jobs"] == 8
    # per tile: classify depends on its 2 detect chunks, product on the
    # classify — cross-stage edges, not a fleet-wide phase barrier
    c0 = queue.job(plan["classify"][0])
    assert c0["depends_on"] == plan["detect"][:2]
    p0 = queue.job(plan["product"][0])
    assert p0["depends_on"] == [plan["classify"][0]]
    # every detect payload carries its chunk's chip ids + the acquired
    d0 = queue.job(plan["detect"][0])
    assert d0["payload"]["acquired"] == ACQ
    assert len(d0["payload"]["cids"]) == 2
    # the product job's bounds cover the SAME chips the detect stage
    # enqueued (a single tile point would cover one chip of the area)
    from firebird_tpu.products import covering_chips
    detected = {tuple(c) for j in plan["detect"][:2]
                for c in map(tuple, queue.job(j)["payload"]["cids"])}
    covered = set(covering_chips(
        [tuple(b) for b in p0["payload"]["bounds"]]))
    assert detected <= covered


def test_plan_builder_validates(queue):
    with pytest.raises(ValueError, match="msday"):
        enqueue_tile_plan(queue, [(0, 0)], acquired=ACQ, msday=1)
    with pytest.raises(ValueError, match="product_dates"):
        enqueue_tile_plan(queue, [(0, 0)], acquired=ACQ,
                          products=["seglength"])


# ---------------------------------------------------------------------------
# Worker loop (toy handlers; injectable sleep — no waits)
# ---------------------------------------------------------------------------

def _worker(cfg, queue, handlers, **kw):
    return FleetWorker(cfg, queue, worker_id="t:1", handlers=handlers,
                       sleep=lambda s: None, **kw)


def test_worker_drains_toy_jobs(tmp_path, queue):
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))
    ran = []
    w = _worker(cfg, queue, {"detect": lambda p, lease: ran.append(p["n"])})
    queue.enqueue("detect", {"n": 1})
    queue.enqueue("detect", {"n": 2})
    summary = w.run(until_drained=True)
    assert ran == [1, 2]
    assert summary["executed"] == 2 and summary["acked"] == 2
    assert summary["queue"]["done"] == 2 and not summary["wedged"]
    assert obs_metrics.counter("fleet_jobs_claimed").value == 2
    assert obs_metrics.counter("fleet_jobs_acked").value == 2
    # per-job-type latency histogram recorded under the dynamic name
    assert obs_metrics.histogram(
        "fleet_job_seconds_detect").snapshot()["count"] == 2


def test_worker_failure_requeues_then_dead_letters(tmp_path, queue):
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))

    def boom(p, lease):
        raise RuntimeError("handler exploded")

    queue.enqueue("detect", {}, max_attempts=2)
    w = _worker(cfg, queue, {"detect": boom})
    summary = w.run(until_drained=True)
    assert summary["requeued"] == 1 and summary["dead"] == 1
    assert summary["queue"]["dead"] == 1 and not summary["wedged"]


def test_worker_abandons_on_stale_fence(tmp_path, queue, clock):
    """A StaleFence out of the handler (a fenced write rejecting) is an
    abandon, not a failure: no fail() report, the successor's queue
    state is untouched, and the loss is counted."""
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))
    jid = queue.enqueue("detect", {})

    def zombie(p, lease):
        clock.advance(31.0)                   # lease lapses mid-job
        queue.claim("successor")              # and a successor re-claims
        raise StaleFence("write rejected")

    w = _worker(cfg, queue, {"detect": zombie})
    summary = w.run(max_jobs=1)
    assert summary["lost"] == 1 and summary["acked"] == 0
    job = queue.job(jid)
    assert job["state"] == "leased" and job["owner"] == "successor"
    assert obs_metrics.counter("fleet_jobs_lost").value == 1


def test_worker_unknown_job_type_dead_letters(tmp_path, queue):
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))
    queue.enqueue("product", {}, max_attempts=1)
    w = _worker(cfg, queue, {"detect": lambda p, lease: None})
    summary = w.run(until_drained=True)
    assert summary["dead"] == 1
    assert queue.status()["dead_errors"] == {"ValueError": 1}


def test_worker_wedge_detection(tmp_path, queue):
    """pending jobs blocked behind a dead dependency + nothing leased =
    polling can never finish; until_drained exits wedged instead of
    spinning forever."""
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))
    d = queue.enqueue("detect", {}, max_attempts=1)
    queue.enqueue("classify", {}, depends_on=[d])

    def boom(p, lease):
        raise RuntimeError("dead upstream")

    w = _worker(cfg, queue, {"detect": boom})
    summary = w.run(until_drained=True)
    assert summary["wedged"]
    assert summary["queue"] == {"pending": 1, "leased": 0, "done": 0,
                                "dead": 1}


def test_worker_beat_paths(tmp_path, queue, clock):
    """One heartbeat attempt: True extends, False (injected lease fault
    — the partition model) skips so the lease just ages, None on loss."""
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 faults="lease:p=1")
    queue.enqueue("detect", {})
    w = _worker(cfg, queue, {})
    lease = queue.claim(w.worker_id)
    expires0 = queue.job(lease.job_id)["lease_expires"]
    assert w._beat(lease) is False            # injected: beat dropped
    assert queue.job(lease.job_id)["lease_expires"] == expires0
    w2 = _worker(Config(store_backend="sqlite",
                        store_path=str(tmp_path / "s.db")), queue, {})
    clock.advance(5.0)
    assert w2._beat(lease) is True            # healthy: lease extended
    assert queue.job(lease.job_id)["lease_expires"] == expires0 + 5.0
    assert obs_metrics.gauge("fleet_lease_age_seconds").value == 5.0
    clock.advance(31.0)
    assert w2._beat(lease) is None            # lapsed: lost


def test_worker_heartbeat_thread_stops_cleanly(tmp_path, queue):
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_heartbeat_sec=0.01)
    queue.enqueue("detect", {"n": 1})
    w = FleetWorker(cfg, queue, worker_id="t:1",
                    handlers={"detect": lambda p, lease: None})
    w.run(max_jobs=1)
    assert not any(t.name.startswith("fleet-heartbeat")
                   for t in threading.enumerate())


def test_lease_fault_plan_grammar():
    from firebird_tpu import faults as faultlib

    plan = faultlib.FaultPlan.parse("lease:p=1")
    inj = plan.injector("lease")
    with pytest.raises(faultlib.InjectedFault):
        inj.fire()
    with pytest.raises(ValueError, match="chip="):
        faultlib.FaultPlan.parse("lease:chip=1:2")


def test_runstatus_fleet_block():
    from firebird_tpu.obs import server as obs_server

    st = obs_server.RunStatus("r", "fleet-worker",
                              fleet=lambda: {"jobs": {"pending": 3}})
    assert st.progress()["fleet"] == {"jobs": {"pending": 3}}
    assert obs_server.RunStatus("r", "x").progress()["fleet"] is None
    boom = obs_server.RunStatus(
        "r", "x", fleet=lambda: (_ for _ in ()).throw(RuntimeError("db")))
    assert "RuntimeError" in boom.progress()["fleet"]["error"]


def test_worker_fleet_block_shape(tmp_path, queue):
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))
    queue.enqueue("detect", {"n": 1})
    w = _worker(cfg, queue, {"detect": lambda p, lease: None})
    w.run(max_jobs=1)
    block = w.fleet_block()
    assert block["jobs"]["done"] == 1
    assert block["worker"]["id"] == "t:1"
    assert block["worker"]["tallies"]["acked"] == 1
    assert block["worker"]["current_job"] is None


def test_manifest_fence_stamp_is_monotonic(tmp_path):
    from firebird_tpu.driver import quarantine as qlib

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"))
    # no manifest + no acquired: nothing to create
    assert qlib.stamp_manifest_fence(cfg, 1, run_id="r") is None
    path = qlib.stamp_manifest_fence(cfg, 5, run_id="r", acquired=ACQ)
    doc = json.load(open(path))
    assert doc["fence"] == 5 and doc["acquired"] == ACQ
    qlib.stamp_manifest_fence(cfg, 3, run_id="r")      # stale: no-op
    assert json.load(open(path))["fence"] == 5
    qlib.stamp_manifest_fence(cfg, 9, run_id="r")      # newer: climbs
    assert json.load(open(path))["fence"] == 9


@pytest.mark.slow
def test_detect_job_acks_minus_dead_letters(tmp_path, queue):
    """A detect job whose chip exhausts its fetch retries acks (the job
    ran; per-chip quarantine is the record) and the dead letter SURVIVES
    the job's redeem sweep — regression: the worker used to discard the
    whole payload's entries, erasing letters recorded seconds earlier."""
    from firebird_tpu import grid
    from firebird_tpu.driver import quarantine as qlib
    from firebird_tpu.utils.fn import take

    cids = list(take(2, grid.chips(grid.tile(x=100, y=200))))
    poisoned = tuple(int(v) for v in cids[0])
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"),
                 source_backend="synthetic", chips_per_batch=1,
                 device_sharding="off", dtype="float64", fetch_retries=0,
                 faults=f"ingest:chip={poisoned[0]}:{poisoned[1]}")
    queue.enqueue("detect", {
        "x": 100, "y": 200, "acquired": ACQ,
        "cids": [[int(a), int(b)] for a, b in cids]})
    w = FleetWorker(cfg, queue, worker_id="t:1", sleep=lambda s: None)
    summary = w.run(until_drained=True)
    assert summary["acked"] == 1              # the job completed...
    q = qlib.Quarantine.load(qlib.quarantine_path(cfg))
    assert q.chip_ids() == {poisoned}         # ...minus its dead letter


# ---------------------------------------------------------------------------
# End to end: fleet-drained plan == direct driver run
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_detect_jobs_match_direct_run(tmp_path):
    """Two detect jobs drained by a fleet worker (real handlers, fenced
    store, real queue) produce segment coverage identical to the direct
    single-process changedetection run — the merged-store half of the
    fleet-smoke acceptance at unit scale (tier-1 budget: slow-marked;
    `make fleet-smoke` proves the same contract with real processes)."""
    from firebird_tpu.driver import core
    from firebird_tpu.store import SqliteStore

    def cfg_for(sub):
        return Config(store_backend="sqlite",
                      store_path=str(tmp_path / sub / "fb.db"),
                      source_backend="synthetic", chips_per_batch=1,
                      device_sharding="off", dtype="float64",
                      fleet_db=str(tmp_path / sub / "queue.db"))

    direct_cfg = cfg_for("direct")
    os.makedirs(tmp_path / "direct", exist_ok=True)
    done = core.changedetection(x=100, y=200, acquired=ACQ, number=2,
                                chunk_size=1, cfg=direct_cfg)
    assert len(done) == 2

    fleet_cfg = cfg_for("fleet")
    os.makedirs(tmp_path / "fleet", exist_ok=True)
    queue = FleetQueue(queue_path(fleet_cfg), lease_sec=300.0)
    plan = enqueue_tile_plan(queue, [(100.0, 200.0)], acquired=ACQ,
                             number=2, chunk_size=1)
    assert len(plan["detect"]) == 2
    w = FleetWorker(fleet_cfg, queue, worker_id="t:1",
                    sleep=lambda s: None)
    summary = w.run(until_drained=True)
    assert summary["acked"] == 2 and summary["queue"]["done"] == 2

    def rows(cfg):
        store = SqliteStore(cfg.store_path, cfg.keyspace())
        out = {}
        for table in ("chip", "pixel", "segment"):
            frame = store.read(table)
            cols = sorted(frame)
            n = len(frame[cols[0]]) if cols else 0
            out[table] = sorted(
                json.dumps([(c, frame[c][i]) for c in cols],
                           sort_keys=True) for i in range(n))
        store.close()
        return out

    assert rows(direct_cfg) == rows(fleet_cfg)
    # the manifest carries the last owning lease's fencing token
    from firebird_tpu.driver import quarantine as qlib
    doc = json.load(open(qlib.manifest_path(fleet_cfg)))
    assert doc["fence"] >= 1 and doc["acquired"] == ACQ
    # re-delivery fast path: re-running the same plan skips stored chips
    queue2 = FleetQueue(queue_path(fleet_cfg), lease_sec=300.0)
    enqueue_tile_plan(queue2, [(100.0, 200.0)], acquired=ACQ,
                      number=2, chunk_size=1)
    w2 = FleetWorker(fleet_cfg, queue2, worker_id="t:2",
                     sleep=lambda s: None)
    s2 = w2.run(until_drained=True)
    assert s2["acked"] == 2
    assert rows(direct_cfg) == rows(fleet_cfg)
    queue.close()
    queue2.close()
