"""Native data plane (firebird_tpu/native): C++ <-> NumPy parity.

The C++ library is an accelerator, not a behavior change: every function
must produce byte-identical results to the NumPy fallback, and the package
must work with FIREBIRD_NO_NATIVE=1.
"""

import base64

import numpy as np
import pytest

from firebird_tpu import native


def _reload_fallback(monkeypatch):
    """A second view of the module forced onto the NumPy path."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)


def test_library_builds():
    # g++ is part of the baked toolchain; the library must compile and load.
    assert native.available()


@pytest.mark.parametrize("n", [0, 1, 2, 3, 57, 20000])
def test_b64_roundtrip(n):
    rng = np.random.default_rng(n)
    raw = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    enc = base64.b64encode(raw)
    assert native.b64_decode(enc) == raw
    assert native.b64_decode(enc.decode()) == raw


def test_b64_whitespace_and_invalid():
    raw = b"hello world!"
    enc = base64.b64encode(raw).decode()
    wrapped = enc[:4] + "\n" + enc[4:8] + " " + enc[8:]
    assert native.b64_decode(wrapped) == raw
    with pytest.raises(ValueError):
        native.b64_decode("@@@@")


def test_b64_int16_payload():
    # The wire shape: 20,000 bytes of little-endian int16 -> [100,100].
    rng = np.random.default_rng(0)
    a = rng.integers(-30000, 30000, (100, 100), dtype=np.int16)
    enc = base64.b64encode(a.astype("<i2").tobytes())
    out = np.frombuffer(native.b64_decode(enc), dtype="<i2").reshape(100, 100)
    np.testing.assert_array_equal(out, a)


@pytest.mark.parametrize("T,cap", [(0, 8), (1, 8), (37, 64), (64, 64)])
def test_pack_spectra_matches_numpy(T, cap):
    rng = np.random.default_rng(T)
    src = rng.integers(-9999, 30000, (7, T, 251), dtype=np.int16)
    got = native.pack_spectra(src, cap, -9999)
    want = np.full((7, 251, cap), -9999, np.int16)
    want[..., :T] = src.transpose(0, 2, 1)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("T,cap", [(0, 8), (37, 64)])
def test_pack_qa_matches_numpy(T, cap):
    rng = np.random.default_rng(T)
    src = rng.integers(0, 2**16, (T, 333), dtype=np.uint16)
    got = native.pack_qa(src, cap, 1)
    want = np.full((333, cap), 1, np.uint16)
    want[:, :T] = src.T
    np.testing.assert_array_equal(got, want)


def test_fallback_b64_strict(monkeypatch):
    """The stdlib fallback matches the native decoder's error contract:
    whitespace skipped, any other invalid character raises ValueError."""
    raw = bytes(range(256)) * 4
    enc = base64.b64encode(raw).decode()
    wrapped = "\n".join(enc[i: i + 76] for i in range(0, len(enc), 76))
    _reload_fallback(monkeypatch)
    assert native.b64_decode(wrapped) == raw
    with pytest.raises(ValueError):
        native.b64_decode("@@@@")
    with pytest.raises(ValueError):
        native.b64_decode("QUJD@@@@RUZH")


def test_fallback_parity(monkeypatch):
    """The NumPy fallback and C++ agree on a full chip-sized workload."""
    rng = np.random.default_rng(7)
    src = rng.integers(-9999, 30000, (7, 120, 10000), dtype=np.int16)
    qa = rng.integers(0, 2**16, (120, 10000), dtype=np.uint16)
    fast_s = native.pack_spectra(src, 128, -9999)
    fast_q = native.pack_qa(qa, 128, 1)
    _reload_fallback(monkeypatch)
    assert not native.available()
    np.testing.assert_array_equal(native.pack_spectra(src, 128, -9999), fast_s)
    np.testing.assert_array_equal(native.pack_qa(qa, 128, 1), fast_q)


def test_pack_uses_out_buffer():
    src = np.zeros((7, 4, 16), np.int16)
    out = np.empty((7, 16, 8), np.int16)
    got = native.pack_spectra(src, 8, -9999, out=out)
    assert got is out
