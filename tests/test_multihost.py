"""Two-process distributed integration test.

SURVEY.md §4 lists "no multi-executor tests" among the reference's gaps;
this closes it for real: two OS processes bring up jax.distributed over a
localhost coordinator (the DCN bootstrap path deploy/README.md documents),
each takes its strided shard of one tile's chips (driver.host_shard), runs
change detection end-to-end through the CLI, and upserts into one shared
sqlite store.  The union of both processes' writes must equal the
single-host enumeration — the framework's multi-host correctness claim.
"""

import glob
import json
import os
import sqlite3
import subprocess
import sys
import time

import pytest
from conftest import free_port as _free_port

from firebird_tpu import grid
from firebird_tpu.obs import report as obs_report


def _run_children(tmp_path, tag, cmd_for, env_for, n=2, timeout=1800):
    """Launch n child processes, wait, return their outputs.

    One log file per child, not pipes: draining piped children
    sequentially can deadlock if the undrained one fills its pipe buffer
    while the other waits in a distributed barrier.  Asserts exit code 0
    for every child (with its output in the failure message).

    The timeout covers a COLD persistent cache: the mesh child's
    capacity retry compiles the sharded program at several capacities,
    and on a fresh host (or after a host change invalidates the cache —
    XLA rejects entries whose machine features mismatch) each is a cold
    multi-minute compile; 900s was measured to be too tight for the
    2-process lockstep in that state (round 4).  A timeout failure
    carries every child's log tail so the hang point is diagnosable.
    """
    procs, logs = [], []
    timed_out = None
    try:
        for i in range(n):
            logs.append(open(tmp_path / f"{tag}{i}.log", "w+"))
            procs.append(subprocess.Popen(
                cmd_for(i), env=env_for(i), stdout=logs[-1],
                stderr=subprocess.STDOUT, text=True))
        deadline = time.monotonic() + timeout   # shared, not per-child
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 1.0))
            except subprocess.TimeoutExpired as e:
                timed_out = e
                break
    finally:
        for p in procs:
            p.kill()
        outs = []
        for f in logs:
            f.seek(0)
            outs.append(f.read())
            f.close()
    if timed_out is not None:
        tails = "\n".join(f"--- child {i} tail ---\n{o[-2000:]}"
                          for i, o in enumerate(outs))
        raise AssertionError(
            f"children not done after {timeout}s\n{tails}") from timed_out
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    return outs


@pytest.mark.slow  # ~35s (two jax subprocess bring-ups); tier-1 keeps the faster two-proc mesh/report rungs below — `make test` still runs the full end-to-end
def test_two_process_changedetection(tmp_path):
    store = tmp_path / "mh.db"
    env_base = dict(os.environ)
    env_base.update({
        "FIREBIRD_JAX_PLATFORM": "cpu",
        "FIREBIRD_SOURCE": "synthetic",
        "FIREBIRD_STORE_BACKEND": "sqlite",
        "FIREBIRD_STORE_PATH": str(store),
        "FIREBIRD_CHIPS_PER_BATCH": "2",
        "FIREBIRD_DEVICE_SHARDING": "off",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "JAX_NUM_PROCESSES": "2",
        # one local device per process — the realistic per-host topology
        # (the suite's 8-virtual-device XLA_FLAGS would inflate both sides)
        "XLA_FLAGS": "",
    })
    cmd = [sys.executable, "-m", "firebird_tpu.cli", "changedetection",
           "-x", "542000", "-y", "1650000",
           "-a", "1995-01-01/1998-01-01", "-n", "4"]
    outs = _run_children(
        tmp_path, "proc", lambda i: cmd,
        lambda i: dict(env_base, JAX_PROCESS_ID=str(i)))

    # each process logged its disjoint strided shard
    joined = "\n".join(outs)
    assert "process 0/2 takes 2 of 4 chips" in joined, joined[-2000:]
    assert "process 1/2 takes 2 of 4 chips" in joined, joined[-2000:]

    # union of both hosts' keyed upserts == the single-host enumeration
    expect = set(grid.chips(grid.tile(542000, 1650000))[:4])
    [db] = glob.glob(str(tmp_path / "mh.*.db"))
    con = sqlite3.connect(db)
    got = set(con.execute("SELECT DISTINCT cx, cy FROM segment").fetchall())
    assert got == expect
    # every pixel of every chip accounted for
    n_pix = con.execute("SELECT COUNT(*) FROM pixel").fetchone()[0]
    assert n_pix == 4 * 10000

    # --- multi-host report aggregation (obs.report) ---
    # each process wrote its own shard next to the shared store...
    shard0 = json.load(open(tmp_path / "obs_report.host0.json"))
    shard1 = json.load(open(tmp_path / "obs_report.host1.json"))
    for i, sh in enumerate((shard0, shard1)):
        obs_report.validate_report(sh)
        assert sh["run"]["process_id"] == i
        assert sh["run_counters"]["chips"] == 2
    # ONE fleet-wide run id: process 0 mints it and broadcasts through
    # the coordination-service KV store (driver.core.fleet_run_id), so
    # both hosts' logs/shards join on the same identifier
    assert shard0["run"]["run_id"] == shard1["run"]["run_id"]
    # ...and process 0 merged them into one fleet obs_report.json whose
    # counters equal the sum of the shards
    fleet = json.load(open(tmp_path / "obs_report.json"))
    obs_report.validate_report(fleet)
    assert fleet["fleet"]["hosts"] == 2
    assert fleet["fleet"]["expected_hosts"] == 2
    assert "missing" not in fleet["fleet"]
    assert fleet["run_counters"]["chips"] == 4
    assert fleet["run_counters"]["pixels"] == 4 * 10000
    for name, fc in fleet["metrics"]["counters"].items():
        assert fc == shard0["metrics"]["counters"].get(name, 0) \
            + shard1["metrics"]["counters"].get(name, 0), name
    for name, fh in fleet["metrics"]["histograms"].items():
        parts = [sh["metrics"]["histograms"].get(name, {"count": 0})
                 for sh in (shard0, shard1)]
        assert fh["count"] == sum(p["count"] for p in parts), name
    # the merged view is what tooling loads for this directory
    assert obs_report.load_fleet_report(str(tmp_path))["fleet"]["hosts"] == 2


def test_global_mesh_two_procs_two_devices(tmp_path):
    """VERDICT r1 weak #4: multi-process x multi-device composition.  Two
    processes x 2 virtual devices form one 4-device global mesh; each
    child asserts detect_sharded's globally-sharded results equal the
    single-device kernel (see tests/_mp_mesh_child.py for the covered
    cross-host paths: array assembly, wcap allgather, capacity-retry
    sync)."""
    coord = f"127.0.0.1:{_free_port()}"
    child = os.path.join(os.path.dirname(__file__), "_mp_mesh_child.py")
    env = dict(os.environ, XLA_FLAGS="")
    try:
        outs = _run_children(
            tmp_path, "mesh",
            lambda i: [sys.executable, child, str(i), coord], lambda i: env)
    except AssertionError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            # jax<0.5's CPU backend cannot compile cross-process SPMD
            # programs at all (XlaRuntimeError at backend_compile) — the
            # path under test only exists on real multi-host accelerator
            # backends there.  Any other child failure still fails.
            pytest.skip("CPU backend lacks multiprocess SPMD compile "
                        "(jax<0.5); global-mesh path needs real "
                        "multi-host hardware on this toolchain")
        raise
    for i, out in enumerate(outs):
        assert f"CHILD_OK {i}" in out
    # the two cadences really did disagree on the local window cap —
    # otherwise the allgather path was not exercised
    caps = {out.split("wcap_local=")[1].split()[0] for out in outs}
    assert len(caps) == 2, outs
