"""Raster export: product rows -> georeferenced ENVI / npy mosaics."""

import json
import os

import numpy as np
from click.testing import CliRunner

from firebird_tpu import cli, export, grid
from firebird_tpu.ccd.params import FILL_VALUE
from firebird_tpu.ingest.packer import CHIP_SIDE, PIXELS
from firebird_tpu.store import MemoryStore, SqliteStore

# Grid-aligned CONUS chip UL (as tests/test_products.py).
CX, CY = -585, 5805
CHIP_M = 3000


def put_product(store, name, date, cx, cy, value):
    cells = np.empty(1, object)
    cells[0] = np.full(PIXELS, value, np.int32).tolist()
    store.write("product", {"name": [name], "date": [date],
                            "cx": [cx], "cy": [cy], "cells": cells})


def test_mosaic_places_chips_and_fills_missing():
    store = MemoryStore()
    # 2x2 chip area; only 3 chips stored -> the 4th fills with FILL_VALUE
    put_product(store, "seglength", "2014-01-01", CX, CY, 11)
    put_product(store, "seglength", "2014-01-01", CX + CHIP_M, CY, 22)
    put_product(store, "seglength", "2014-01-01", CX, CY - CHIP_M, 33)
    bounds = [(CX + 10, CY - 10), (CX + 2 * CHIP_M - 10, CY - 2 * CHIP_M + 10)]
    cells, ulx, uly = export.mosaic("seglength", "2014-01-01", bounds, store)
    assert (ulx, uly) == (CX, CY)
    assert cells.shape == (2 * CHIP_SIDE, 2 * CHIP_SIDE)
    assert np.all(cells[:CHIP_SIDE, :CHIP_SIDE] == 11)
    assert np.all(cells[:CHIP_SIDE, CHIP_SIDE:] == 22)
    assert np.all(cells[CHIP_SIDE:, :CHIP_SIDE] == 33)
    assert np.all(cells[CHIP_SIDE:, CHIP_SIDE:] == FILL_VALUE)


def test_export_envi_roundtrip(tmp_path):
    store = MemoryStore()
    put_product(store, "curveqa", "2010-06-01", CX, CY, 8)
    bounds = [(CX + 10, CY - 10)]
    paths = export.export(["curveqa"], ["2010-06-01"], bounds,
                          str(tmp_path), fmt="envi", store=store)
    dat = next(p for p in paths if p.endswith(".dat"))
    hdr = next(p for p in paths if p.endswith(".hdr"))
    arr = np.fromfile(dat, "<i4").reshape(CHIP_SIDE, CHIP_SIDE)
    assert np.all(arr == 8)
    text = open(hdr).read()
    assert f"samples = {CHIP_SIDE}" in text and f"lines = {CHIP_SIDE}" in text
    assert "data type = 3" in text
    assert f"{float(CX):.1f}" in text and f"{float(CY):.1f}" in text
    assert "Albers" in text and grid.CONUS_ALBERS_PROJ[:20] in text


def test_export_npy_sidecar(tmp_path):
    store = MemoryStore()
    put_product(store, "ccd", "2011-01-01", CX, CY, 60)
    paths = export.export(["ccd"], ["2011-01-01"], [(CX + 10, CY - 10)],
                          str(tmp_path), fmt="npy", store=store)
    arr = np.load(next(p for p in paths if p.endswith(".npy")))
    assert arr.shape == (CHIP_SIDE, CHIP_SIDE) and np.all(arr == 60)
    meta = json.load(open(next(p for p in paths if p.endswith(".json"))))
    assert meta["ulx"] == CX and meta["uly"] == CY
    assert meta["pixel_size_m"] == 30.0 and meta["fill"] == FILL_VALUE
    assert meta["crs_wkt"].startswith("PROJCS")


def test_export_rejects_unknown(tmp_path):
    store = MemoryStore()
    for bad in (dict(products=["nope"], dates=["2011-01-01"], fmt="envi"),
                dict(products=["ccd"], dates=["2011-01-01"], fmt="tiff"),
                dict(products=["ccd"], dates=["2011/01/01"], fmt="envi")):
        try:
            export.export(bad["products"], bad["dates"],
                          [(CX, CY)], str(tmp_path), fmt=bad["fmt"],
                          store=store)
            raise AssertionError("expected ValueError")
        except ValueError:
            pass


def test_cli_export_end_to_end(tmp_path, monkeypatch):
    db = str(tmp_path / "fb.db")
    monkeypatch.setenv("FIREBIRD_STORE_BACKEND", "sqlite")
    monkeypatch.setenv("FIREBIRD_STORE_PATH", db)
    from firebird_tpu.config import Config

    store = SqliteStore(db, Config.from_env().keyspace())
    put_product(store, "seglength", "2014-01-01", CX, CY, 5)
    out = str(tmp_path / "rasters")
    res = CliRunner().invoke(cli.entrypoint, [
        "export", "-b", f"{CX + 10},{CY - 10}", "-p", "seglength",
        "-d", "2014-01-01", "-o", out, "-f", "npy"])
    assert res.exit_code == 0, res.output
    npy = os.path.join(out, "seglength_2014-01-01.npy")
    assert npy in res.output
    assert np.all(np.load(npy) == 5)


def test_mosaic_rejects_cell_count_sensor_mismatch():
    # A Sentinel-2 campaign's rows must not silently mis-georeference
    # through Landsat geometry (ADVICE r1): cell-count disagreement with
    # the sensor spec fails loudly.
    import pytest

    from firebird_tpu.ccd.sensor import SENTINEL2

    store = MemoryStore()
    put_product(store, "seglength", "2014-01-01", CX, CY, 7)  # 100x100 cells
    bounds = [(CX + 10, CY - 10)]
    with pytest.raises(ValueError, match="sentinel2"):
        export.mosaic("seglength", "2014-01-01", bounds, store,
                      sensor=SENTINEL2)


# ---------------------------------------------------------------------------
# Bounds edge cases feeding the pyramid (docs/SERVING.md)
# ---------------------------------------------------------------------------

def test_mosaic_single_chip_bounds():
    """One interior point -> exactly the containing chip, ulx/uly at
    the chip's (grid-aligned) UL corner even for a non-aligned point."""
    store = MemoryStore()
    put_product(store, "curveqa", "2014-01-01", CX, CY, 5)
    cells, ulx, uly = export.mosaic(
        "curveqa", "2014-01-01", [(CX + 1234.5, CY - 987.6)], store)
    assert cells.shape == (CHIP_SIDE, CHIP_SIDE)
    assert (ulx, uly) == (CX, CY)
    assert np.all(cells == 5)


def test_mosaic_non_aligned_bounds_snap_outward():
    """Non-chip-aligned bounds SNAP to the covering chips (they never
    shift the raster off-grid): a 1 m sliver across a chip edge covers
    both chips, and the mosaic's UL is the UL chip's corner."""
    store = MemoryStore()
    put_product(store, "curveqa", "2014-01-01", CX, CY, 1)
    put_product(store, "curveqa", "2014-01-01", CX + CHIP_M, CY, 2)
    bounds = [(CX + CHIP_M - 0.5, CY - 10.0),
              (CX + CHIP_M + 0.5, CY - 20.0)]
    cells, ulx, uly = export.mosaic("curveqa", "2014-01-01", bounds, store)
    assert (ulx, uly) == (CX, CY)
    assert cells.shape == (CHIP_SIDE, 2 * CHIP_SIDE)
    assert np.all(cells[:, :CHIP_SIDE] == 1)
    assert np.all(cells[:, CHIP_SIDE:] == 2)


def test_mosaic_stored_row_size_mismatch_message():
    """A stored row whose cell count disagrees with the sensor chip
    geometry must reject with the pass-the-campaign's-sensor message,
    not mis-georeference (the pyramid's base renderer leans on this)."""
    import pytest

    store = MemoryStore()
    cells = np.empty(1, object)
    cells[0] = [7] * 64          # not 100x100
    store.write("product", {"name": ["curveqa"], "date": ["2014-01-01"],
                            "cx": [CX], "cy": [CY], "cells": cells})
    with pytest.raises(ValueError,
                       match="pass the campaign's sensor"):
        export.mosaic("curveqa", "2014-01-01", [(CX + 1, CY - 1)], store)


def test_pyramid_bounds_reject_off_domain(tmp_path):
    """Bounds feeding the pyramid must reject chips outside the quadkey
    domain with the domain message (a map tile cannot address them) —
    the mosaic itself happily snaps any bounds, so the rejection
    belongs to (and happens at) the pyramid layer."""
    import pytest

    from firebird_tpu.serve import pyramid as pyr

    store = MemoryStore()
    p = pyr.TilePyramid(str(tmp_path), pyr.store_read_chip(store))
    with pytest.raises(ValueError, match="quadkey domain"):
        p.build_area(["curveqa"], ["2014-01-01"],
                     [(-9_000_000.0, CY)], levels=1)
