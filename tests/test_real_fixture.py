"""Recorded-service-response fixture (tests/data/recorded/, VERDICT r2
task 8): the reference pins its data plane to recorded Chipmunk responses
(its test/conftest.py:20-37); these tests consume the same recorded BYTES
through this repo's decode -> pack -> kernel chain.

The recorded chip raster (le07_srb1 at (-1815585,1064805), 2002-12-21)
is entirely fill (-9999) — the upstream never recorded live spectra — so
what it pins end-to-end is the wire decode (base64 LE int16 through the
native plane) and the all-fill/no-data contract: NODATA procedure, zero
segments, all-False processing mask, sentinel format rows.
"""

import base64
import json
from pathlib import Path

import numpy as np
import pytest

from firebird_tpu.ccd import kernel, params
from firebird_tpu.ccd.reference import detect as oracle_detect
from firebird_tpu.ccd.sensor import LANDSAT_ARD
from firebird_tpu.ingest.sources import ChipData, decode_raster
from firebird_tpu.ingest.packer import pack, pixel_timeseries

DATA = Path(__file__).parent / "data" / "recorded"


@pytest.fixture(scope="module")
def recorded_chip():
    return json.loads((DATA / "chip_response.json").read_text())[0]


def test_recorded_wire_decode(recorded_chip):
    """decode_raster reproduces a plain numpy decode of the recorded
    response bit for bit (the native b64 plane vs np.frombuffer)."""
    got = decode_raster(recorded_chip)
    want = np.frombuffer(base64.b64decode(recorded_chip["data"]),
                         dtype=np.int16).reshape(100, 100)
    assert got.dtype == np.int16 and got.shape == (100, 100)
    np.testing.assert_array_equal(got, want)
    # the recorded raster is known-degenerate: all fill
    assert np.all(got == params.FILL_VALUE)
    assert recorded_chip["ubid"] == "le07_srb1"


def test_recorded_fill_chip_end_to_end(recorded_chip):
    """A chip built from the recorded all-fill raster runs the full
    pack -> kernel chain to the reference's no-data contract, and the f64
    oracle agrees on sampled pixels."""
    raster = decode_raster(recorded_chip)
    T = 4
    dates = np.array([730000 + 16 * i for i in range(T)], np.int64)
    spectra = np.broadcast_to(
        raster.reshape(1, 1, 100, 100), (7, T, 100, 100)).copy()
    qas = np.full((T, 100, 100), 1 << params.QA_FILL_BIT, np.uint16)
    chip = ChipData(cx=int(recorded_chip["x"]), cy=int(recorded_chip["y"]),
                    dates=dates, spectra=spectra, qas=qas,
                    sensor=LANDSAT_ARD)
    p = pack([chip], bucket=4)
    seg = kernel.chip_slice(kernel.detect_packed(p), 0, to_host=True)
    assert np.all(np.asarray(seg.n_segments) == 0)
    assert not np.asarray(seg.mask).any()
    assert np.all(np.asarray(seg.procedure) == kernel.PROC_NODATA)
    for pix in (0, 4999, 9999):
        rec = kernel.segments_to_records(seg, dates, pix)
        o = oracle_detect(**pixel_timeseries(p, 0, pix))
        assert rec["procedure"] == o["procedure"] == "no-data"
        assert rec["change_models"] == []
        assert rec["processing_mask"] == o["processing_mask"]
