"""Store tests: write-then-read round trips per table and idempotent
upserts — the reference's pattern minus the live Cassandra container
(test/test_cassandra.py, test_chip/pixel/segment/tile.py)."""

import numpy as np
import pytest

from firebird_tpu.store import AsyncWriter, MemoryStore, ParquetStore, SqliteStore, open_store
from firebird_tpu.store.schema import TABLES


def seg_frame(cx=1, cy=2, px=3, py=4, sday="1999-01-01", chprob=1.0):
    f = {"cx": [cx], "cy": [cy], "px": [px], "py": [py],
         "sday": [sday], "eday": ["2000-01-01"], "bday": [sday],
         "chprob": [chprob], "curqa": [8], "rfrawp": [None]}
    for p in ("bl", "gr", "re", "ni", "s1", "s2", "th"):
        f[f"{p}mag"] = [1.5]
        f[f"{p}rmse"] = [0.5]
        f[f"{p}coef"] = [[0.1, 0.2, 0.3]]
        f[f"{p}int"] = [7.0]
    return f


def make_stores(tmp_path):
    return [MemoryStore("ks"),
            SqliteStore(str(tmp_path / "s.db"), "ks"),
            ParquetStore(str(tmp_path / "pq"), "ks")]


@pytest.mark.parametrize("backend", ["memory", "sqlite", "parquet"])
def test_roundtrip_all_tables(tmp_path, backend):
    store = open_store(backend, str(tmp_path / "st"), "ks")
    store.write("chip", {"cx": [10], "cy": [20],
                         "dates": [["1999-01-01", "1999-02-01"]]})
    store.write("pixel", {"cx": [10], "cy": [20], "px": [10], "py": [20],
                          "mask": [[1, 0]]})
    store.write("segment", seg_frame(cx=10, cy=20))
    store.write("tile", {"tx": [1], "ty": [2], "name": ["rf"],
                         "model": ["BLOB"], "updated": ["2020-01-01"]})
    assert store.read("chip", {"cx": 10, "cy": 20})["dates"][0] == \
        ["1999-01-01", "1999-02-01"]
    assert store.read("pixel")["mask"][0] == [1, 0]
    seg = store.read("segment")
    assert seg["blcoef"][0] == [0.1, 0.2, 0.3]
    assert seg["chprob"][0] == 1.0
    assert store.read("tile")["model"] == ["BLOB"]
    store.close()


@pytest.mark.parametrize("backend", ["memory", "sqlite"])
def test_upsert_idempotence(tmp_path, backend):
    """Rerunning the same keys must not duplicate rows — the reference's
    durability model (PK upserts, SURVEY.md §5)."""
    store = open_store(backend, str(tmp_path / "st"), "ks")
    store.write("segment", seg_frame(chprob=0.5))
    store.write("segment", seg_frame(chprob=0.9))  # same key, new value
    out = store.read("segment")
    assert len(out["cx"]) == 1
    assert out["chprob"][0] == 0.9
    # different sday -> second row (sday is part of the segment key)
    store.write("segment", seg_frame(sday="2001-01-01"))
    assert store.count("segment") == 2
    store.close()


def test_parquet_chip_rewrite_idempotent(tmp_path):
    store = ParquetStore(str(tmp_path / "pq"), "ks")
    store.write("segment", seg_frame(cx=5, cy=6, chprob=0.1))
    store.write("segment", seg_frame(cx=5, cy=6, chprob=0.7))
    out = store.read("segment", {"cx": 5})
    assert len(out["cx"]) == 1 and out["chprob"][0] == 0.7


def test_keyspace_isolation(tmp_path):
    a = SqliteStore(str(tmp_path / "s.db"), "ks_a")
    b = SqliteStore(str(tmp_path / "s.db"), "ks_b")
    a.write("tile", {"tx": [1], "ty": [1], "name": ["m"], "model": ["A"],
                     "updated": ["x"]})
    assert b.count("tile") == 0


def test_async_writer_drains_and_raises(tmp_path):
    store = MemoryStore()
    w = AsyncWriter(store)
    for i in range(20):
        w.write("chip", {"cx": [i], "cy": [0], "dates": [["1999-01-01"]]})
    w.flush()
    assert store.count("chip") == 20

    class Boom(MemoryStore):
        def write(self, table, frame):
            raise RuntimeError("disk full")

    w2 = AsyncWriter(Boom())
    w2.write("chip", {"cx": [1], "cy": [0], "dates": [[]]})
    with pytest.raises(RuntimeError, match="disk full"):
        w2.flush()
    w.close()


def test_schema_matches_reference_column_set():
    """Segment column set mirrors ccdc/segment.py:16-56 (38 cols: 9 meta +
    28 band + rfrawp); chip/pixel/tile match their modules."""
    seg_cols = [c for c, _ in TABLES["segment"]["columns"]]
    assert len(seg_cols) == 38
    for p in ("bl", "gr", "re", "ni", "s1", "s2", "th"):
        for suffix in ("mag", "rmse", "coef", "int"):
            assert f"{p}{suffix}" in seg_cols
    assert TABLES["segment"]["key"] == ("cx", "cy", "px", "py", "sday", "eday")
    assert [c for c, _ in TABLES["chip"]["columns"]] == ["cx", "cy", "dates"]
    assert [c for c, _ in TABLES["pixel"]["columns"]] == \
        ["cx", "cy", "px", "py", "mask"]
    assert [c for c, _ in TABLES["tile"]["columns"]] == \
        ["tx", "ty", "name", "model", "updated"]
