"""Store tests: write-then-read round trips per table and idempotent
upserts — the reference's pattern minus the live Cassandra container
(test/test_cassandra.py, test_chip/pixel/segment/tile.py)."""

import re
import sqlite3

import numpy as np
import pytest

from firebird_tpu.store import (AsyncWriter, CassandraStore, MemoryStore,
                                ParquetStore, SqliteStore, open_store)
from firebird_tpu.store.schema import TABLES, primary_key


def seg_frame(cx=1, cy=2, px=3, py=4, sday="1999-01-01", chprob=1.0):
    f = {"cx": [cx], "cy": [cy], "px": [px], "py": [py],
         "sday": [sday], "eday": ["2000-01-01"], "bday": [sday],
         "chprob": [chprob], "curqa": [8], "rfrawp": [None]}
    for p in ("bl", "gr", "re", "ni", "s1", "s2", "th"):
        f[f"{p}mag"] = [1.5]
        f[f"{p}rmse"] = [0.5]
        f[f"{p}coef"] = [[0.1, 0.2, 0.3]]
        f[f"{p}int"] = [7.0]
    return f


def make_stores(tmp_path):
    return [MemoryStore("ks"),
            SqliteStore(str(tmp_path / "s.db"), "ks"),
            ParquetStore(str(tmp_path / "pq"), "ks")]


@pytest.mark.parametrize("backend", ["memory", "sqlite", "parquet"])
def test_roundtrip_all_tables(tmp_path, backend):
    store = open_store(backend, str(tmp_path / "st"), "ks")
    store.write("chip", {"cx": [10], "cy": [20],
                         "dates": [["1999-01-01", "1999-02-01"]]})
    store.write("pixel", {"cx": [10], "cy": [20], "px": [10], "py": [20],
                          "mask": [[1, 0]]})
    store.write("segment", seg_frame(cx=10, cy=20))
    store.write("tile", {"tx": [1], "ty": [2], "name": ["rf"],
                         "model": ["BLOB"], "updated": ["2020-01-01"]})
    assert store.read("chip", {"cx": 10, "cy": 20})["dates"][0] == \
        ["1999-01-01", "1999-02-01"]
    assert store.read("pixel")["mask"][0] == [1, 0]
    seg = store.read("segment")
    assert seg["blcoef"][0] == [0.1, 0.2, 0.3]
    assert seg["chprob"][0] == 1.0
    assert store.read("tile")["model"] == ["BLOB"]
    store.close()


@pytest.mark.parametrize("backend", ["memory", "sqlite"])
def test_upsert_idempotence(tmp_path, backend):
    """Rerunning the same keys must not duplicate rows — the reference's
    durability model (PK upserts, SURVEY.md §5)."""
    store = open_store(backend, str(tmp_path / "st"), "ks")
    store.write("segment", seg_frame(chprob=0.5))
    store.write("segment", seg_frame(chprob=0.9))  # same key, new value
    out = store.read("segment")
    assert len(out["cx"]) == 1
    assert out["chprob"][0] == 0.9
    # different sday -> second row (sday is part of the segment key)
    store.write("segment", seg_frame(sday="2001-01-01"))
    assert store.count("segment") == 2
    store.close()


def test_parquet_chip_rewrite_idempotent(tmp_path):
    store = ParquetStore(str(tmp_path / "pq"), "ks")
    store.write("segment", seg_frame(cx=5, cy=6, chprob=0.1))
    store.write("segment", seg_frame(cx=5, cy=6, chprob=0.7))
    out = store.read("segment", {"cx": 5})
    assert len(out["cx"]) == 1 and out["chprob"][0] == 0.7


def test_keyspace_isolation(tmp_path):
    a = SqliteStore(str(tmp_path / "s.db"), "ks_a")
    b = SqliteStore(str(tmp_path / "s.db"), "ks_b")
    a.write("tile", {"tx": [1], "ty": [1], "name": ["m"], "model": ["A"],
                     "updated": ["x"]})
    assert b.count("tile") == 0


def test_async_writer_keyed_ordering():
    """Frames sharing a key drain in submission order even with many
    workers — the driver's resume invariant (segment frame last per
    chip)."""
    order: dict[tuple, list] = {}
    lock = __import__("threading").Lock()

    class Recorder(MemoryStore):
        def write(self, table, frame):
            k = (frame["cx"][0], frame["cy"][0])
            with lock:
                order.setdefault(k, []).append(table)
            return 1

    w = AsyncWriter(Recorder(), workers=4)
    for i in range(24):
        cid = (i, 0)
        for t in ("chip", "pixel", "segment"):
            w.write(t, {"cx": [i], "cy": [0]}, key=cid)
    w.flush()
    w.close()
    assert len(order) == 24
    for seq in order.values():
        assert seq == ["chip", "pixel", "segment"]


def test_async_writer_multiworker_raises_on_error():
    class Boom(MemoryStore):
        def write(self, table, frame):
            raise RuntimeError("disk full")

    w = AsyncWriter(Boom(), workers=3)
    # the error may surface from write() (if a worker already failed) or
    # from flush() — both are the contract
    with pytest.raises(RuntimeError, match="disk full"):
        for i in range(6):
            w.write("chip", {"cx": [i], "cy": [0], "dates": [[]]}, key=(i,))
        w.flush()
    w.close()


def test_queue_depth_gauge_drains_on_flush_failure():
    """Regression: the store_queue_depth gauge must read 0 after a flush
    whose writes FAILED, not just after successful drains — a failing
    backend must not leave a phantom backlog on the egress-backpressure
    signal (and the failure itself must still be counted + raised).

    The backend gates on an event so every frame is verifiably queued
    (gauge > 0) before the first failure fires — no interleaving can
    short-circuit the test through write()'s error re-raise path."""
    import threading

    from firebird_tpu.obs import metrics as obs_metrics

    obs_metrics.reset_registry()
    gate = threading.Event()

    class Boom(MemoryStore):
        def write(self, table, frame):
            gate.wait(timeout=10)
            raise RuntimeError("disk full")

    w = AsyncWriter(Boom(), workers=2)
    for i in range(8):
        w.write("chip", {"cx": [i], "cy": [0], "dates": [[]]}, key=(i,))
    # a real backlog exists while the backend is stuck
    assert obs_metrics.gauge("store_queue_depth").value > 0
    gate.set()
    with pytest.raises(RuntimeError, match="disk full"):
        w.flush()
    # all queued frames drained (through the failure path) by flush time
    assert obs_metrics.gauge("store_queue_depth").value == 0
    assert obs_metrics.counter("store_write_errors").value >= 1
    w.close()
    assert obs_metrics.gauge("store_queue_depth").value == 0


def test_async_writer_drains_and_raises(tmp_path):
    store = MemoryStore()
    w = AsyncWriter(store)
    for i in range(20):
        w.write("chip", {"cx": [i], "cy": [0], "dates": [["1999-01-01"]]})
    w.flush()
    assert store.count("chip") == 20

    class Boom(MemoryStore):
        def write(self, table, frame):
            raise RuntimeError("disk full")

    w2 = AsyncWriter(Boom())
    w2.write("chip", {"cx": [1], "cy": [0], "dates": [[]]})
    with pytest.raises(RuntimeError, match="disk full"):
        w2.flush()
    w.close()


def test_async_writer_worker_survives_base_exception():
    """The writer.py BaseException branch (previously untested): a
    backend raising KeyboardInterrupt must not kill the worker thread
    with un-acked queue items (flush would hang forever) — the item is
    acked, the error surfaces from flush as a wrapped Exception, and the
    writer keeps working afterward."""
    calls = {"n": 0}

    class Interrupted(MemoryStore):
        def write(self, table, frame):
            calls["n"] += 1
            if calls["n"] == 1:
                raise KeyboardInterrupt("operator mashed ^C")
            return super().write(table, frame)

    store = Interrupted()
    w = AsyncWriter(store)
    w.write("chip", {"cx": [1], "cy": [0], "dates": [[]]})
    with pytest.raises(RuntimeError, match="writer interrupted"):
        w.flush()                       # surfaces, does NOT hang
    assert all(t.is_alive() for t in w._threads)
    # the worker is still functional: later writes land normally
    w.write("chip", {"cx": [2], "cy": [0], "dates": [[]]})
    w.flush()
    assert store.count("chip") == 1
    w.close()


def test_async_writer_retry_policy_heals_brownout():
    """A store brownout shorter than the retry budget heals inline: no
    error reaches flush, every row lands, and the retries are counted as
    store_write_retries (the chaos-smoke store path in miniature)."""
    from firebird_tpu.obs import metrics as obs_metrics
    from firebird_tpu.retry import RetryPolicy

    obs_metrics.reset_registry()
    calls = {"n": 0}

    class Brownout(MemoryStore):
        def write(self, table, frame):
            calls["n"] += 1
            if calls["n"] in (2, 3):   # two consecutive failures
                raise IOError("store browned out")
            return super().write(table, frame)

    store = Brownout()
    w = AsyncWriter(store, retry=RetryPolicy(3, sleep=lambda s: None,
                                             counter_name="store_write_retries"))
    for i in range(4):
        w.write("chip", {"cx": [i], "cy": [0], "dates": [[]]}, key=(i,))
    w.flush()                           # heals: nothing raises
    w.close()
    assert store.count("chip") == 4
    assert obs_metrics.counter("store_write_retries").value == 2
    assert obs_metrics.counter("store_write_errors").value == 0


# ---------------------------------------------------------------------------
# Cassandra backend (injectable-session seam; no cluster needed)
# ---------------------------------------------------------------------------

class FakePrepared:
    def __init__(self, cql):
        self.cql = cql
        m = re.match(r"INSERT INTO \w+\.(\w+) \(([^)]*)\)", cql)
        self.table = m.group(1)
        self.cols = [c.strip() for c in m.group(2).split(",")]


class FakeFuture:
    def __init__(self):
        self.done = False

    def result(self):
        self.done = True


class FakeCqlSession:
    """Executes the exact CQL shapes CassandraStore generates against an
    in-memory table dict — enough to run the generic round-trip tests."""

    def __init__(self):
        self.ddl: list[str] = []
        self.tables: dict[str, dict] = {}
        self.max_in_flight = 0
        self._in_flight: list[FakeFuture] = []

    def prepare(self, cql):
        return FakePrepared(cql)

    def execute_async(self, stmt, params):
        row = dict(zip(stmt.cols, params))
        key = tuple(row[k] for k in primary_key(stmt.table))
        self.tables.setdefault(stmt.table, {})[key] = row
        f = FakeFuture()
        self._in_flight = [x for x in self._in_flight if not x.done] + [f]
        self.max_in_flight = max(self.max_in_flight, len(self._in_flight))
        return f

    def execute(self, cql, params=()):
        if cql.startswith(("CREATE KEYSPACE", "CREATE TABLE")):
            self.ddl.append(cql)
            return []
        m = re.match(r"SELECT (.+) FROM \w+\.(\w+)(?: WHERE (.+?))?"
                     r"(?: ALLOW FILTERING)?$", cql)
        cols, table, where = m.group(1), m.group(2), m.group(3)
        rows = list(self.tables.get(table, {}).values())
        if where:
            keys = re.findall(r"(\w+) = %s", where)
            rows = [r for r in rows
                    if all(r.get(k) == v for k, v in zip(keys, params))]
        if cols.startswith("COUNT"):
            return [(len(rows),)]
        distinct = cols.startswith("DISTINCT ")
        names = [c.strip() for c in cols.removeprefix("DISTINCT ").split(",")]
        out = [tuple(r.get(c) for c in names) for r in rows]
        return list(dict.fromkeys(out)) if distinct else out


def test_cassandra_roundtrip_all_tables():
    sess = FakeCqlSession()
    store = CassandraStore(keyspace="ks", session=sess)
    store.write("chip", {"cx": [10], "cy": [20],
                         "dates": [["1999-01-01", "1999-02-01"]]})
    store.write("segment", seg_frame(cx=10, cy=20))
    assert store.read("chip", {"cx": 10, "cy": 20})["dates"][0] == \
        ["1999-01-01", "1999-02-01"]
    seg = store.read("segment")
    assert seg["blcoef"][0] == [0.1, 0.2, 0.3]
    assert store.count("segment") == 1
    assert store.chip_ids("segment") == {(10, 20)}


def test_cassandra_schema_parity():
    """DDL mirrors resources/schema.cql key design: partition key = first
    two key columns, remaining key columns clustering."""
    sess = FakeCqlSession()
    CassandraStore(keyspace="my-ks!", session=sess)
    assert any("CREATE KEYSPACE IF NOT EXISTS my_ks_" in d for d in sess.ddl)
    seg_ddl = next(d for d in sess.ddl if ".segment" in d)
    assert "PRIMARY KEY ((cx, cy), px, py, sday, eday)" in seg_ddl
    chip_ddl = next(d for d in sess.ddl if ".chip" in d)
    assert "PRIMARY KEY ((cx, cy))" in chip_ddl


def test_cassandra_ddl_generator_matches_backend():
    """`firebird schema` prints exactly what CassandraStore executes (the
    reference's resources/schema.cql + `make db-schema` path)."""
    from firebird_tpu.store import cassandra_ddl

    sess = FakeCqlSession()
    CassandraStore(keyspace="my-ks!", session=sess)
    assert sess.ddl == cassandra_ddl("my-ks!")
    assert [d for d in cassandra_ddl("ks") if "CREATE TABLE" in d] \
        and all(t in " ".join(cassandra_ddl("ks"))
                for t in ("chip", "pixel", "segment", "tile", "product"))
    # unquoted CQL identifiers must start with a letter: digit- and
    # underscore-leading names get the ks_ prefix (deploy/README.md)
    from firebird_tpu.store.backends import sanitize_keyspace

    assert sanitize_keyspace("!prod") == "ks__prod"
    assert sanitize_keyspace("_prod") == "ks__prod"
    assert sanitize_keyspace("9lives") == "ks_9lives"
    assert sanitize_keyspace("") == "default"


def test_cli_schema_command():
    from click.testing import CliRunner

    from firebird_tpu.cli import entrypoint

    res = CliRunner().invoke(entrypoint, ["schema", "-k", "1bad ks!"])
    assert res.exit_code == 0, res.output
    assert "CREATE KEYSPACE IF NOT EXISTS ks_1bad_ks_" in res.output
    for t in ("chip", "pixel", "segment", "tile", "product"):
        assert f"ks_1bad_ks_.{t} " in res.output
    assert res.output.rstrip().endswith(";")


def test_cassandra_upsert_and_bounded_writes():
    sess = FakeCqlSession()
    store = CassandraStore(keyspace="ks", session=sess, concurrent_writes=2)
    f = seg_frame(chprob=0.5)
    multi = {k: v * 50 for k, v in f.items()}
    multi["px"] = list(range(50))
    store.write("segment", multi)
    assert store.count("segment") == 50
    assert sess.max_in_flight <= 3     # 2 waiting + the one being issued
    # same-key rewrite upserts
    store.write("segment", seg_frame(chprob=0.9))
    before = store.count("segment")
    store.write("segment", seg_frame(chprob=0.2))
    assert store.count("segment") == before


def test_cassandra_missing_driver_is_clear():
    try:
        import cassandra  # noqa: F401
        pytest.skip("cassandra-driver is installed here")
    except ImportError:
        pass
    with pytest.raises(RuntimeError, match="cassandra-driver"):
        CassandraStore(keyspace="ks")


def test_schema_matches_reference_column_set():
    """Segment column set mirrors ccdc/segment.py:16-56 (38 cols: 9 meta +
    28 band + rfrawp); chip/pixel/tile match their modules."""
    seg_cols = [c for c, _ in TABLES["segment"]["columns"]]
    assert len(seg_cols) == 38
    for p in ("bl", "gr", "re", "ni", "s1", "s2", "th"):
        for suffix in ("mag", "rmse", "coef", "int"):
            assert f"{p}{suffix}" in seg_cols
    assert TABLES["segment"]["key"] == ("cx", "cy", "px", "py", "sday", "eday")
    assert [c for c, _ in TABLES["chip"]["columns"]] == ["cx", "cy", "dates"]
    assert [c for c, _ in TABLES["pixel"]["columns"]] == \
        ["cx", "cy", "px", "py", "mask"]
    assert [c for c, _ in TABLES["tile"]["columns"]] == \
        ["tx", "ty", "name", "model", "updated"]


def test_sqlite_chip_reads_use_secondary_index(tmp_path):
    """The serve-path point read `WHERE cx=? AND cy=?` must be
    index-backed on BOTH result tables.  The segment PK's autoindex
    already leads with (cx, cy), but the product PK leads with
    (name, date) — without idx_product_chip a per-chip product read
    scans the whole table (backends.SqliteStore._create)."""
    store = SqliteStore(str(tmp_path / "idx.db"), "ks")
    try:
        con = store._conn()
        for table in ("segment", "product"):
            plan = " ".join(
                row[3] for row in con.execute(
                    f'EXPLAIN QUERY PLAN SELECT * FROM "{table}" '
                    "WHERE cx = ? AND cy = ?", (1, 2)))
            assert "USING INDEX" in plan.upper(), \
                f"{table} chip read is not index-backed: {plan}"
            assert "SCAN" not in plan.upper(), \
                f"{table} chip read scans: {plan}"
        # the product index is the explicit secondary one
        plan = " ".join(
            row[3] for row in con.execute(
                'EXPLAIN QUERY PLAN SELECT * FROM "product" '
                "WHERE cx = ? AND cy = ?", (1, 2)))
        assert "idx_product_chip" in plan
    finally:
        store.close()


# ---------------------------------------------------------------------------
# Read-only replica connections (serve fleet; docs/SERVING.md)
# ---------------------------------------------------------------------------

def test_sqlite_read_only_replica_cannot_write(tmp_path):
    """A mode=ro replica open can read everything and write NOTHING —
    neither through the refusing facade nor past it at the SQL layer
    (PRAGMA query_only)."""
    import pytest

    path = str(tmp_path / "repl.db")
    writer = SqliteStore(path, "t")
    writer.write("segment", {
        "cx": [1], "cy": [2], "px": [1], "py": [2],
        "sday": ["1995-01-01"], "eday": ["1999-01-01"],
        "bday": ["0001-01-01"], "chprob": [0.0], "curqa": [4],
    })
    replica = open_store("sqlite", path, "t", read_only=True)
    try:
        assert replica.read("segment", {"cx": 1, "cy": 2})["px"] == [1]
        assert replica.count("segment") == 1
        with pytest.raises(RuntimeError, match="read-only replica"):
            replica.write("segment", {"cx": [9], "cy": [9], "px": [9],
                                      "py": [9]})
        # defense in depth: even a raw statement on the connection is
        # refused by PRAGMA query_only / the ro VFS open
        with pytest.raises(sqlite3.OperationalError):
            replica._conn().execute(
                'INSERT INTO "segment" (cx, cy, px, py) '
                "VALUES (9, 9, 9, 9)")
    finally:
        replica.close()
        writer.close()


def test_sqlite_read_only_requires_existing_db(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError, match="read-only replica"):
        open_store("sqlite", str(tmp_path / "nope.db"), "t",
                   read_only=True)
    with pytest.raises(ValueError, match="replica mode"):
        open_store("memory", "", "t", read_only=True)


def test_read_only_replica_does_not_block_live_writer(tmp_path):
    """The satellite regression: N replicas reading a WAL store must
    never contend on the writer's lock — a replica holding a long read
    cannot stall a live AsyncWriter flush."""
    import threading
    import time

    path = str(tmp_path / "live.db")
    store = SqliteStore(path, "t")
    frame = {
        "cx": [5], "cy": [6], "px": [5], "py": [6],
        "sday": ["1995-01-01"], "eday": ["1999-01-01"],
        "bday": ["0001-01-01"], "chprob": [0.0], "curqa": [4],
    }
    store.write("segment", frame)
    replica = open_store("sqlite", path, "t", read_only=True)
    stop = threading.Event()

    def read_loop():
        while not stop.is_set():
            replica.read("segment")

    readers = [threading.Thread(target=read_loop, daemon=True)
               for _ in range(3)]
    for t in readers:
        t.start()
    w = AsyncWriter(store)
    try:
        t0 = time.monotonic()
        for i in range(30):
            w.write("segment", dict(frame, px=[5 + i]), key=(5, 6))
            if i % 10 == 9:
                w.flush()
        elapsed = time.monotonic() - t0
        # WAL: writer never waits on readers.  The generous bound only
        # fails if the replica actually BLOCKED the writer (the
        # pre-mode=ro failure was 'database is locked' stalls).
        assert elapsed < 20.0
    finally:
        w.close()
        stop.set()
        for t in readers:
            t.join(5)
        replica.close()
        store.close()
