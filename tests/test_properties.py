"""Property-based tests (hypothesis) for the pure layers.

SURVEY.md §4 lists "no property-based tests" among the reference's gaps.
The grid geometry and date utilities are total pure functions over large
domains — exactly where generative testing earns its keep.  Coordinates
generate as whole meters (every LCMAP grid/chip coordinate is integral),
keeping floor-snap properties exact rather than float-boundary flaky.
"""

import pytest

# Not in the baked container image (no network installs); skip cleanly
# instead of erroring the whole module at collection.
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, strategies as st  # noqa: E402

from firebird_tpu import grid
from firebird_tpu.utils import dates as dt

# CONUS Albers projection coordinates span roughly these bounds.
coords = st.integers(min_value=-2_500_000, max_value=3_500_000)


@given(st.integers(-50, 50), st.integers(-50, 50))
def test_grid_proj_roundtrip(h, v):
    for g in (grid.CONUS.tile, grid.CONUS.chip):
        x, y = grid.proj_pt(h, v, g)
        assert grid.grid_pt(x, y, g) == (h, v)


@given(coords, coords)
def test_snap_idempotent(x, y):
    s = grid.snap(x, y)
    for level in ("tile", "chip"):
        px, py = s[level]["proj-pt"]
        again = grid.snap(px, py)[level]
        assert again["grid-pt"] == s[level]["grid-pt"]
        assert again["proj-pt"] == (px, py)


@given(coords, coords)
def test_point_lands_inside_its_tile(x, y):
    t = grid.tile(x, y)
    assert t["ulx"] <= x < t["lrx"]
    assert t["lry"] < y <= t["uly"]


@given(coords, coords)
def test_tile_chips_partition_the_tile(x, y):
    """Every tile has exactly 50x50 distinct chips, all inside its extents,
    snapping back to themselves on the chip grid."""
    t = grid.tile(x, y)
    cids = grid.chips(t)
    assert len(cids) == 2500 and len(set(cids)) == 2500
    for cx, cy in (cids[0], cids[49], cids[-1]):
        assert t["ulx"] <= cx < t["lrx"]
        assert t["lry"] < cy <= t["uly"]
        assert grid.snap(cx, cy)["chip"]["proj-pt"] == (cx, cy)


@given(coords, coords, coords, coords)
def test_cells_for_bounds_cover_their_points(x0, y0, x1, y1):
    """Every bound point's tile is in the enumeration, and the enumeration
    is exactly the covering rectangle (no gaps, no extras)."""
    recs = grid.tiles_for_bounds([(x0, y0), (x1, y1)])
    hv = {(r["h"], r["v"]) for r in recs}
    for px, py in ((x0, y0), (x1, y1)):
        assert grid.grid_pt(px, py, grid.CONUS.tile) in hv
    hs = {h for h, _ in hv}
    vs = {v for _, v in hv}
    assert len(hv) == len(hs) * len(vs)    # full rectangle


@given(st.integers(1, 3_650_000))
def test_ordinal_iso_roundtrip(o):
    assert dt.to_ordinal(dt.to_iso(o)) == o
