"""Random-forest layer tests: the 33-feature contract (ccdc/features.py:20-37),
the TPU-native forest trainer/inference, model serialization, and the
completed classification pipeline (ccdc/core.py:156-251 incl. the path the
reference left commented out)."""

import numpy as np
import pytest

from firebird_tpu.config import Config
from firebird_tpu.driver import core
from firebird_tpu.ingest import SyntheticSource
from firebird_tpu.rf import features, forest, pipeline
from firebird_tpu.store import MemoryStore
from firebird_tpu.utils import dates as dt

# ---------------------------------------------------------------------------
# Feature contract
# ---------------------------------------------------------------------------

REFERENCE_COLUMNS = [
    'blmag', 'grmag', 'remag', 'nimag', 's1mag', 's2mag', 'thmag',
    'blrmse', 'grrmse', 'rermse', 'nirmse', 's1rmse', 's2rmse', 'thrmse',
    'blcoef', 'grcoef', 'recoef', 'nicoef', 's1coef', 's2coef', 'thcoef',
    'blint', 'grint', 'reint', 'niint', 's1int', 's2int', 'thint',
    'dem', 'aspect', 'slope', 'mpw', 'posidex']


def test_columns_contract():
    """Order is significant; altering invalidates persisted models
    (ccdc/features.py:28-36)."""
    assert list(features.COLUMNS) == REFERENCE_COLUMNS
    assert len(features.COLUMNS) == 33


def _seg_frame(cx, cy, rows):
    """Minimal segment frame: rows = [(px, py, sday, eday)]."""
    n = len(rows)
    frame = {
        "cx": [cx] * n, "cy": [cy] * n,
        "px": [r[0] for r in rows], "py": [r[1] for r in rows],
        "sday": [r[2] for r in rows], "eday": [r[3] for r in rows],
        "bday": [r[3] for r in rows],
        "chprob": [1.0] * n, "curqa": [8] * n, "rfrawp": [None] * n,
    }
    for p in ("bl", "gr", "re", "ni", "s1", "s2", "th"):
        frame[f"{p}mag"] = list(np.arange(n, dtype=float))
        frame[f"{p}rmse"] = [0.5] * n
        frame[f"{p}coef"] = [[10.0 + i, 2.0, 3.0, 0, 0, 0, 0] for i in range(n)]
        frame[f"{p}int"] = [7.0] * n
    return frame


def test_assemble_first_coefficient_rule():
    """densify takes first(x) of list-valued columns (ccdc/udfs.py:19-21):
    only coefficient 0 becomes a feature."""
    cx, cy = 3000, 6000
    seg = _seg_frame(cx, cy, [(cx, cy, "1990-01-01", "1995-01-01"),
                              (cx + 30, cy - 60, "1990-01-01", "1995-01-01")])
    aux = {name: np.full((100, 100), i + 1.0)
           for i, name in enumerate(features.AUX_FEATURES)}
    X, meta = features.assemble(seg, aux, cx, cy)
    assert X.shape == (2, 33)
    j = list(features.COLUMNS).index("blcoef")
    np.testing.assert_allclose(X[:, j], [10.0, 11.0])   # first coef only
    # aux gathered at (px, py): row1 is pixel (1 east, 2 south)
    j = list(features.COLUMNS).index("dem")
    np.testing.assert_allclose(X[:, j], [1.0, 1.0])
    assert meta["px"] == [cx, cx + 30]


def test_segment_window_and_sentinels():
    cx, cy = 0, 0
    seg = _seg_frame(cx, cy, [
        (0, 0, "1990-01-01", "1995-01-01"),
        (30, 0, "1985-01-01", "1995-01-01"),    # starts before window
        (60, 0, "0001-01-01", "0001-01-01"),    # sentinel
    ])
    w = features.segment_window(seg, dt.to_ordinal("1989-01-01"),
                                dt.to_ordinal("1996-01-01"))
    r = features.real_rows(seg)
    assert list(w & r) == [True, False, False]


# ---------------------------------------------------------------------------
# Forest
# ---------------------------------------------------------------------------

def _blobs(n=1500, f=6, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n)
    centers = rng.normal(0, 5, (classes, f))
    X = centers[y] + rng.normal(0, 1.0, (n, f))
    return X.astype(np.float32), y + 10     # labels need not be 0-based


def test_forest_accuracy_and_roundtrip():
    X, y = _blobs()
    m = forest.train(X, y, n_trees=24, max_depth=6, n_bins=32, seed=1)
    acc = (m.predict(X) == y).mean()
    assert acc > 0.95
    # rawPrediction: one normalized distribution per tree, summed
    raw = m.raw_predict(X[:10])
    assert raw.shape == (10, m.n_classes)
    np.testing.assert_allclose(raw.sum(axis=1), 24.0, rtol=1e-4)
    # serialization round-trip preserves predictions exactly
    m2 = forest.RandomForest.loads(m.dumps())
    np.testing.assert_array_equal(m.raw_predict(X[:50]), m2.raw_predict(X[:50]))


def test_forest_class_order_and_nan_rows():
    X, y = _blobs(n=600, classes=2, seed=3)
    # class imbalance: StringIndexer orders by descending frequency
    keep = (y == 10) | (np.arange(600) % 3 == 0)
    X, y = X[keep], y[keep]
    Xn = X.copy()
    Xn[0, 0] = np.nan                       # dropped from training
    m = forest.train(Xn, y, n_trees=8, max_depth=5, n_bins=16, seed=0)
    assert m.classes[0] == 10               # majority class first
    # NaN at inference routes left deterministically, still returns a class
    p = m.predict(np.full((2, X.shape[1]), np.nan, np.float32))
    assert all(v in m.classes for v in p)


def test_forest_generalizes():
    X, y = _blobs(n=2000, seed=5)
    m = forest.train(X[:1500], y[:1500], n_trees=24, max_depth=6, seed=2)
    assert (m.predict(X[1500:]) == y[1500:]).mean() > 0.9


# ---------------------------------------------------------------------------
# Pipeline end-to-end
# ---------------------------------------------------------------------------

ACQ = "1995-01-01/1997-06-01"
# device_sharding='off': full-chip dispatches must not pad 1 -> 8 virtual
# devices (the sharded path is covered by test_driver/test_parallel).
CFG = Config(store_backend="memory", source_backend="synthetic",
             chips_per_batch=1, dtype="float64", device_sharding="off")


@pytest.fixture(scope="module")
def detected_store():
    store = MemoryStore("test")
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    core.changedetection(x=100, y=200, acquired=ACQ, number=2, chunk_size=2,
                         cfg=CFG, source=src, store=store)
    return store, src


def test_classify_tile_end_to_end(detected_store):
    store, src = detected_store
    model = pipeline.classify_tile(
        100, 200, msday=dt.to_ordinal("1990-01-01"),
        meday=dt.to_ordinal("1999-01-01"), acquired=ACQ, cfg=CFG,
        aux_source=src, store=store, n_trees=8, max_depth=5, n_bins=16)
    assert model is not None
    # model persisted under the tile key (ccdc/tile.py)
    from firebird_tpu import grid
    t = grid.tile(100, 200)
    loaded = pipeline.load_model(store, t["x"], t["y"])
    assert loaded is not None and loaded.n_trees == 8
    # every real segment of the detected chips got an rfrawp vector
    (cx, cy) = sorted(store.chip_ids("segment"))[0]
    seg = store.read("segment", {"cx": cx, "cy": cy})
    real = [i for i, s in enumerate(seg["sday"]) if s != "0001-01-01"]
    scored = [i for i in real if seg["rfrawp"][i] is not None]
    assert len(scored) == len(real) and len(real) > 0
    assert len(seg["rfrawp"][scored[0]]) == model.n_classes
    # labels predicted are within the synthetic trends alphabet (1..8)
    top = np.argmax(np.asarray(seg["rfrawp"][scored[0]], float))
    assert model.classes[top] in range(1, 9)


def test_classify_tile_no_features(detected_store):
    """Training window excluding every segment -> None (randomforest.py:76)."""
    store, src = detected_store
    model = pipeline.classify_tile(
        100, 200, msday=dt.to_ordinal("2050-01-01"),
        meday=dt.to_ordinal("2051-01-01"), acquired=ACQ, cfg=CFG,
        aux_source=src, store=store, n_trees=4, max_depth=3)
    assert model is None


def test_dense_inference_matches_walk():
    """The accelerator (dense leaf-reachability) and CPU (node-walk)
    inference kernels must agree to f32 accumulation order."""
    rng = np.random.default_rng(9)
    X = rng.normal(0, 1, (400, 33)).astype(np.float32)
    y = rng.integers(1, 9, 400)
    m = forest.train(X, y, n_trees=48)
    Xq = rng.normal(0, 1, (600, 33)).astype(np.float32)
    Xq[0, :5] = np.nan                      # NaN routes left in both
    a = m.raw_predict(Xq, batch=512, dense=False)
    b = m.raw_predict(Xq, batch=512, dense=True)
    np.testing.assert_allclose(a, b, atol=1e-4)
    # predictions agree wherever the top-2 classes aren't within
    # accumulation noise of each other (ties may flip either way)
    top2 = np.sort(a, axis=1)[:, -2:]
    decided = (top2[:, 1] - top2[:, 0]) > 1e-3
    assert decided.any()
    assert (a.argmax(1) == b.argmax(1))[decided].all()
