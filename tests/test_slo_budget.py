"""Error-budget plane: the durable metric series store (obs/series.py),
multi-window burn-rate budgets (obs/slo.py), the black-box canary
prober's units (obs/prober.py), and their CLI/endpoint surfaces.  The
cross-PROCESS end-to-end drill — a live fleet with an injected serve
brownout and a stalled watcher — is `make slo-smoke`
(tools/slo_smoke.py); these tests pin the unit contracts the smoke
builds on."""

import json
import os
import time
import urllib.request

import pytest

from firebird_tpu.config import Config
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import series as obs_series
from firebird_tpu.obs import slo as slomod


@pytest.fixture
def fresh_metrics():
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


# A fixed "now" far from the test host's clock: every bucket assertion
# below only holds if ingestion keys on the EMITTER's stamps.
T0 = 1_700_000_000.0


def _snap(t, role="worker", pid=7, counters=None, gauges=None,
          hists=None):
    return {"kind": "snap", "t": t, "role": role, "pid": pid,
            "metrics": {"counters": counters or {},
                        "gauges": gauges or {},
                        "histograms": hists or {}}}


def _hist(count, s, bounds, counts):
    return {"count": count, "sum": s, "bucket_bounds": list(bounds),
            "bucket_counts": list(counts)}


def _write_spool(directory, role, pid, snaps):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"spool.{role}.{pid}.0.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "header", "role": role, "pid": pid,
                            "run_id": f"run-{role}", "segment": 0,
                            "t": 0.0}) + "\n")
        for doc in snaps:
            f.write(json.dumps(doc) + "\n")


# ---------------------------------------------------------------------------
# Series store: ring files, idempotency, the emitter-clock rule
# ---------------------------------------------------------------------------

def test_series_buckets_key_on_emitter_stamps_never_reader_clock(tmp_path):
    """Regression for the clock-domain rule: a snap line's bucket comes
    from the wall-clock the EMITTING process stamped, so two hosts with
    skewed clocks land in their own stamps' buckets and a reader
    re-ingesting years-old spools reproduces the original timeline —
    nothing keys on time.time() in the ingesting process."""
    _write_spool(str(tmp_path), "worker", 42,
                 [_snap(T0 + 5.0, counters={"c": 1.0})])
    # a second host, 12h skewed
    _write_spool(str(tmp_path), "serve", 43,
                 [_snap(T0 + 43_200.0, counters={"c": 9.0})])
    store = obs_series.SeriesStore(str(tmp_path / "series"))
    assert store.ingest_spools(str(tmp_path)) > 0
    pts = obs_series.read_points(str(tmp_path / "series"), 10)
    by_src = {p["src"]: p for p in pts}
    assert by_src["worker:42"]["b"] == int((T0 + 5.0) // 10)
    assert by_src["serve:43"]["b"] == int((T0 + 43_200.0) // 10)
    # and none of them anywhere near the reader's own clock
    assert all(abs(p["b"] * 10 - time.time()) > 86_400 * 365 for p in pts)
    store.close()


def test_series_reingest_is_idempotent_across_restart(tmp_path):
    events = [_snap(T0 + i * 20.0, counters={"c": float(i)})
              for i in range(5)]
    store = obs_series.SeriesStore(str(tmp_path))
    assert store.ingest_events(events) > 0
    assert store.ingest_events(events) == 0          # same process
    store.close()
    store2 = obs_series.SeriesStore(str(tmp_path))   # restarted reader
    assert store2.ingest_events(events) == 0         # state from disk
    store2.close()


def test_series_history_survives_reopen_then_write(tmp_path):
    """Regression: the first post-reopen write used to truncate
    segment 0 for this pid (mode 'w'), destroying every point a prior
    same-process incarnation durably wrote — while the restored dedup
    state kept the destroyed points from ever re-ingesting.  A
    reopened store must RESUME its ring in append mode."""
    store = obs_series.SeriesStore(str(tmp_path), resolutions=(10,))
    events = [_snap(T0 + i * 20.0, counters={"c": float(i)})
              for i in range(10)]
    assert store.ingest_events(events) == 10
    store.close()
    store2 = obs_series.SeriesStore(str(tmp_path), resolutions=(10,))
    assert store2.ingest_events(
        [_snap(T0 + 500.0, counters={"c": 99.0})]) == 1
    store2.close()
    pts = obs_series.read_points(str(tmp_path), 10)
    assert [p["m"]["counters"]["c"] for p in pts] == \
        [float(i) for i in range(10)] + [99.0]


def test_series_reopen_resumes_ring_position(tmp_path):
    """A reopened store resumes its NEWEST segment and rotates onward
    from there — truncation only happens when the ring genuinely wraps
    onto a segment."""
    store = obs_series.SeriesStore(str(tmp_path), points_per_segment=2,
                                   segments=3, resolutions=(10,))
    for i in range(3):
        store.ingest_events(
            [_snap(T0 + i * 20.0, counters={"c": float(i)})])
    store.close()
    # seg 0 is full (2 points), seg 1 holds 1 — resume appends to seg 1
    store2 = obs_series.SeriesStore(str(tmp_path), points_per_segment=2,
                                    segments=3, resolutions=(10,))
    assert store2.ingest_events(
        [_snap(T0 + 60.0, counters={"c": 3.0})]) == 1
    store2.close()
    pid = os.getpid()
    names = sorted(p.name for p in tmp_path.iterdir())
    assert names == [f"series.10.{pid}.{s}.jsonl" for s in (0, 1)]
    pts = obs_series.read_points(str(tmp_path), 10)
    assert [p["m"]["counters"]["c"] for p in pts] == [0.0, 1.0, 2.0, 3.0]


def test_series_gc_reclaims_stale_dead_incarnation_files(tmp_path):
    """Ring files whose whole content has aged past their resolution's
    ring retention are unlinked at open (dead cron/CI incarnations must
    not grow the directory without bound); a dead-but-FRESH file
    survives — a SIGKILL'd process's recent history is the point of the
    store — and staleness is judged in the emitters' clock domain
    (newest same-resolution point), never the reader's wall clock."""

    def _pt(t, src):
        return json.dumps(
            {"kind": "pt", "res": 10, "b": int(t // 10), "t": t,
             "src": src, "m": {"counters": {}, "gauges": {},
                               "histograms": {}}}) + "\n"

    store = obs_series.SeriesStore(str(tmp_path), points_per_segment=4,
                                   segments=2, resolutions=(10,))
    store.ingest_events([_snap(T0, counters={"c": 1.0})])
    store.close()
    # retention horizon is 4 x 2 x 10s = 80s behind the newest point
    stale = tmp_path / "series.10.999999.0.jsonl"
    stale.write_text(_pt(T0 - 900.0, "worker:1"))
    fresh = tmp_path / "series.10.999998.0.jsonl"
    fresh.write_text(_pt(T0 - 30.0, "worker:2"))
    store2 = obs_series.SeriesStore(str(tmp_path), points_per_segment=4,
                                    segments=2, resolutions=(10,))
    assert store2.status()["gc_removed"] == 1
    store2.close()
    names = {p.name for p in tmp_path.iterdir()}
    assert stale.name not in names and fresh.name in names
    srcs = obs_series.sources(obs_series.read_points(str(tmp_path), 10))
    assert srcs == ["worker:2", "worker:7"]


def test_series_live_bucket_refresh_is_throttled(tmp_path):
    store = obs_series.SeriesStore(str(tmp_path), resolutions=(80,))
    assert store.ingest_events([_snap(T0, counters={"c": 1.0})]) == 1
    # same bucket, under res/8 later: throttled
    assert store.ingest_events(
        [_snap(T0 + 5.0, counters={"c": 2.0})]) == 0
    # same bucket, past the throttle: refreshed
    assert store.ingest_events(
        [_snap(T0 + 11.0, counters={"c": 3.0})]) == 1
    # an older bucket arriving later is immutable past: dropped
    assert store.ingest_events(
        [_snap(T0 - 500.0, counters={"c": 0.5})]) == 0
    store.close()


def test_series_segment_ring_is_bounded(tmp_path):
    store = obs_series.SeriesStore(str(tmp_path), points_per_segment=4,
                                   segments=2, resolutions=(10,))
    for i in range(40):
        store.ingest_events(
            [_snap(T0 + i * 10.0, counters={"c": float(i)})])
    store.close()
    segs = sorted(p.name for p in tmp_path.iterdir())
    pid = os.getpid()
    assert segs == [f"series.10.{pid}.{s}.jsonl" for s in (0, 1)]
    # the ring retains the newest points, oldest truncated away
    pts = obs_series.read_points(str(tmp_path), 10)
    assert pts and pts[-1]["b"] == int((T0 + 390.0) // 10)
    assert len(pts) <= 8


def test_read_points_dedupes_and_windows(tmp_path):
    store = obs_series.SeriesStore(str(tmp_path), resolutions=(10,))
    store.ingest_events([_snap(T0 + 1.0, counters={"c": 1.0}),
                         _snap(T0 + 9.0, counters={"c": 2.0}),
                         _snap(T0 + 21.0, counters={"c": 3.0})])
    store.close()
    pts = obs_series.read_points(str(tmp_path), 10)
    # same bucket collapses keep-latest (batch pre-group)
    assert [p["m"]["counters"]["c"] for p in pts] == [2.0, 3.0]
    # (t0, t1] window edges
    assert obs_series.read_points(str(tmp_path), 10, T0 + 9.0) == pts[1:]
    assert obs_series.read_points(str(tmp_path), 10, None, T0 + 9.0) \
        == pts[:1]
    assert obs_series.sources(pts) == ["worker:7"]


def test_counter_window_sums_per_source_deltas():
    pts = []
    for t, src, v in ((T0 + 10, "worker:1", 10.0),
                      (T0 + 100, "worker:1", 30.0),
                      (T0 + 110, "worker:2", 5.0)):
        pts.append({"kind": "pt", "res": 10, "b": int(t // 10), "t": t,
                    "src": src,
                    "m": {"counters": {"c": v}, "gauges": {},
                          "histograms": {}}})
    # worker:1 delta 20 (baseline point at t<=t0), worker:2 born inside
    # the window baselines at zero: its full cumulative 5 counts
    assert obs_series.counter_window(pts, "c", T0 + 50, T0 + 200) == 25.0
    # empty window is no data, never zero activity
    assert obs_series.counter_window(pts, "c", T0 + 500, T0 + 900) is None
    assert obs_series.counter_window(pts, "other", T0, T0 + 200) == 0.0


def test_hist_window_merges_deltas_and_over_threshold():
    def pt(t, src, h):
        return {"kind": "pt", "res": 10, "b": int(t // 10), "t": t,
                "src": src, "m": {"counters": {}, "gauges": {},
                                  "histograms": {"h_seconds": h}}}

    pts = [pt(T0 + 10, "a:1", _hist(4, 2.0, (1.0, 5.0), (4, 0, 0))),
           pt(T0 + 100, "a:1", _hist(10, 20.0, (1.0, 5.0), (6, 2, 2))),
           pt(T0 + 100, "b:2", _hist(3, 9.0, (1.0, 5.0), (0, 3, 0)))]
    win = obs_series.hist_window(pts, "h_seconds", T0 + 50, T0 + 200)
    # a:1 delta (6, [2,2,2]) + b:2 born-inside (3, [0,3,0])
    assert win["count"] == 9.0
    assert win["bucket_counts"] == [2.0, 5.0, 2.0]
    # over 1.0s: everything past the first bucket
    assert obs_series.hist_over_threshold(win, 1.0) == 7.0
    assert obs_series.hist_over_threshold(win, 5.0) == 2.0
    assert obs_series.hist_window(pts, "h_seconds", T0 + 500,
                                  T0 + 900) is None


def test_bucket_series_per_kind():
    def pt(t, c, g):
        return {"kind": "pt", "res": 10, "b": int(t // 10), "t": t,
                "src": "w:1",
                "m": {"counters": {"c": c}, "gauges": {"g": g},
                      "histograms": {}}}

    pts = [pt(T0 + 5, 10.0, 1.0), pt(T0 + 15, 25.0, 2.0),
           pt(T0 + 25, 25.0, 3.0)]
    # counters render as per-bucket activity deltas
    assert obs_series.bucket_series(pts, "c", "counter", 10) == [
        (int(T0 // 10), 10.0), (int(T0 // 10) + 1, 15.0),
        (int(T0 // 10) + 2, 0.0)]
    # gauges as the merged in-bucket sample
    assert [v for _, v in
            obs_series.bucket_series(pts, "g", "gauge", 10)] == \
        [1.0, 2.0, 3.0]


def test_open_store_zero_cost_paths(tmp_path):
    assert obs_series.open_store(Config(telemetry=0)) is None
    assert obs_series.open_store(
        Config(series=0, series_dir=str(tmp_path))) is None
    # memory backend without an explicit dir: homeless, disabled
    assert obs_series.open_store(Config(store_backend="memory")) is None
    store = obs_series.open_store(Config(series_dir=str(tmp_path)))
    assert store is not None and store.dir == str(tmp_path)
    store.close()


# ---------------------------------------------------------------------------
# Budget grammar + config fail-fast
# ---------------------------------------------------------------------------

def test_budget_spec_grammar():
    (b,) = slomod.parse_budget_spec("alert_freshness<60@99.9/28d")
    assert b["name"] == "alert_freshness" and b["threshold"] == 60.0
    assert b["target_pct"] == 99.9 and b["window_sec"] == 28 * 86400.0
    (r,) = slomod.parse_budget_spec("probe_errors@99/1d")
    assert r["kind"] == "ratio" and r["threshold"] is None
    assert slomod.parse_budget_spec("") == []
    with pytest.raises(ValueError, match="unknown budget objective"):
        slomod.parse_budget_spec("bogus<1@99/1d")
    with pytest.raises(ValueError, match="watchdog-kind"):
        slomod.parse_budget_spec("freshness<600@99/1d")
    with pytest.raises(ValueError, match="takes no"):
        slomod.parse_budget_spec("probe_errors<1@99/1d")
    with pytest.raises(ValueError, match="needs a <threshold"):
        slomod.parse_budget_spec("serve_p99@99/1d")
    with pytest.raises(ValueError, match="missing its /window"):
        slomod.parse_budget_spec("serve_p99<2@99")
    with pytest.raises(ValueError, match="not\\s+<number>"):
        slomod.parse_budget_spec("serve_p99<2@99/soon")
    with pytest.raises(ValueError, match="percentage"):
        slomod.parse_budget_spec("serve_p99<2@100/1d")
    # the default spec must parse (the knob's fallback path)
    assert slomod.parse_budget_spec(slomod.DEFAULT_BUDGET_SPEC)


def test_budget_config_fail_fast():
    Config(slo_budget="serve_p99<2@99/7d")               # valid
    Config(slo_budget="0")                               # disabled
    with pytest.raises(ValueError):
        Config(slo_budget="nope<1@99/1d")
    with pytest.raises(ValueError, match="two scales"):
        Config(slo_fast_sec=600.0, slo_slow_sec=600.0)
    with pytest.raises(ValueError):
        Config(slo_burn=0.0)
    with pytest.raises(ValueError):
        Config(series=-1)
    with pytest.raises(ValueError):
        Config(series_segments=1)


# ---------------------------------------------------------------------------
# Budget evaluation: no-data semantics, burn, exhaustion, durable events
# ---------------------------------------------------------------------------

def test_budget_no_data_contributes_zero_burn(tmp_path):
    """Satellite contract: an objective whose metric never reported is
    ok=null with ZERO burn — never a violation, never banked credit —
    and names its empty windows."""
    v = slomod.evaluate_budgets(str(tmp_path), "probe_errors@99/1d",
                                now=T0)
    assert v["ok"] is True and v["violations"] == 0
    (b,) = v["budgets"]
    assert b["ok"] is None and not b["exhausted"] and not b["burning"]
    assert b["empty_windows"] == ["window", "fast", "slow"]
    assert b["fast_burn"] is None and b["budget_spent"] is None


def test_budget_partial_data_names_empty_windows(tmp_path):
    """Data old enough to miss the fast window must not page: burning
    needs BOTH burn windows non-empty, and the report says which window
    was blind."""
    store = obs_series.SeriesStore(str(tmp_path))
    bad = _hist(10, 100.0, (2.0,), (0, 10))    # all observations > 2s
    store.ingest_events([
        _snap(T0 - 2000.0, role="serve", pid=9,
              hists={"serve_request_seconds": _hist(0, 0.0, (2.0,),
                                                    (0, 0))}),
        _snap(T0 - 1000.0, role="serve", pid=9,
              hists={"serve_request_seconds": bad})])
    store.close()
    v = slomod.evaluate_budgets(str(tmp_path), "serve_p99<2@99/7d",
                                now=T0)
    (b,) = v["budgets"]
    assert b["empty_windows"] == ["fast"]
    assert b["burning"] is False               # fast window is blind
    assert b["exhausted"] is True              # 10 bad of 10 >> 1%
    assert b["ok"] is False and v["ok"] is False


def test_budget_burning_and_exhaustion_from_ratio_counters(tmp_path):
    """A failing canary: both burn windows over threshold pages, and
    cumulative bad over the full window exhausts the budget."""
    store = obs_series.SeriesStore(str(tmp_path))
    store.ingest_events([
        _snap(T0 - 3000.0, role="prober", pid=5,
              counters={"probe_attempts": 10.0, "probe_failures": 0.0}),
        # the fast-window baseline must sit inside the evaluator's
        # 2-bucket lookback before the window edge (T0-320 at res 10)
        _snap(T0 - 310.0, role="prober", pid=5,
              counters={"probe_attempts": 90.0, "probe_failures": 40.0}),
        _snap(T0 - 100.0, role="prober", pid=5,
              counters={"probe_attempts": 100.0,
                        "probe_failures": 50.0})])
    store.close()
    v = slomod.evaluate_budgets(str(tmp_path), "probe_errors@99/1d",
                                now=T0)
    assert v["sources"] == ["prober:5"]
    (b,) = v["budgets"]
    # fast window: 10 of 10 attempts failed -> burn 100x; slow: 50/100
    assert b["fast_burn"] == pytest.approx(100.0)
    assert b["slow_burn"] == pytest.approx(50.0)
    assert b["burning"] is True
    assert b["total"] == 100.0 and b["bad"] == 50.0
    assert b["exhausted"] is True and b["budget_spent"] == 50.0
    assert b["ok"] is False and v["violations"] == 1

    # a healthy canary over the same shape: no page, budget intact
    for f in tmp_path.iterdir():
        f.unlink()
    store = obs_series.SeriesStore(str(tmp_path))
    store.ingest_events([
        _snap(T0 - 3000.0, role="prober", pid=5,
              counters={"probe_attempts": 10.0, "probe_failures": 0.0}),
        _snap(T0 - 100.0, role="prober", pid=5,
              counters={"probe_attempts": 500.0,
                        "probe_failures": 0.0})])
    store.close()
    v = slomod.evaluate_budgets(str(tmp_path), "probe_errors@99/1d",
                                now=T0)
    (b,) = v["budgets"]
    assert b["ok"] is True and b["fast_burn"] == 0.0


def test_budget_burn_decision_uses_unrounded_ratio(tmp_path):
    """Display rounding must not leak into paging: a window burning at
    14.3996x REPORTS 14.4 (3-decimal rounding) but must not page a
    14.4 threshold — and a threshold just under the true ratio must."""
    store = obs_series.SeriesStore(str(tmp_path))
    store.ingest_events([
        # a long clean history keeps the full 1d window unexhausted
        _snap(T0 - 4000.0, role="prober", pid=5,
              counters={"probe_attempts": 10_000_000.0,
                        "probe_failures": 0.0}),
        # fast-window baseline inside the 2-bucket lookback (res 10)
        _snap(T0 - 310.0, role="prober", pid=5,
              counters={"probe_attempts": 10_000_000.0,
                        "probe_failures": 0.0}),
        # 35_999 / 250_000 = 0.143996 -> burn 14.3996x at 99% target
        _snap(T0 - 100.0, role="prober", pid=5,
              counters={"probe_attempts": 10_250_000.0,
                        "probe_failures": 35_999.0})])
    store.close()
    v = slomod.evaluate_budgets(str(tmp_path), "probe_errors@99/1d",
                                now=T0, burn_threshold=14.4)
    (b,) = v["budgets"]
    assert b["fast_burn"] == 14.4 and b["slow_burn"] == 14.4
    assert b["burning"] is False and b["exhausted"] is False
    assert b["ok"] is True and v["ok"] is True
    # the true (unrounded) ratio still pages a threshold it exceeds
    v = slomod.evaluate_budgets(str(tmp_path), "probe_errors@99/1d",
                                now=T0, burn_threshold=14.39)
    assert v["budgets"][0]["burning"] is True


def test_budget_events_record_transitions_only(tmp_path):
    def verdict(state):
        return {"budgets": [{
            "name": "probe_errors", "bad": 5.0, "total": 10.0,
            "allowed_bad": 0.1, "window_sec": 300.0,
            "fast_burn": 50.0, "slow_burn": 50.0,
            "exhausted": state == "exhausted",
            "burning": state in ("burning", "exhausted"),
            "ok": None if state == "no_data" else state == "ok"}]}

    d = str(tmp_path)
    # ok with no prior trouble: not an incident, nothing recorded
    assert slomod.record_budget_events(d, verdict("ok"), now=T0) == []
    (ev,) = slomod.record_budget_events(d, verdict("burning"), now=T0)
    assert ev["state"] == "burning" and ev["prev"] is None
    # steady state repeats are not re-recorded
    assert slomod.record_budget_events(d, verdict("burning"),
                                       now=T0 + 1) == []
    (ev2,) = slomod.record_budget_events(d, verdict("exhausted"),
                                         now=T0 + 2)
    assert ev2["state"] == "exhausted" and ev2["prev"] == "burning"
    (ev3,) = slomod.record_budget_events(d, verdict("ok"), now=T0 + 3)
    assert ev3["state"] == "ok" and ev3["prev"] == "exhausted"
    # ok <-> no_data flaps are not an incident timeline
    assert slomod.record_budget_events(d, verdict("no_data"),
                                       now=T0 + 4) == []
    states = [e["state"] for e in slomod.read_budget_events(d)]
    assert states == ["burning", "exhausted", "ok"]
    # and the log survives a torn tail line
    with open(slomod.budget_events_path(d), "a") as f:
        f.write('{"name": "torn')
    assert [e["state"] for e in slomod.read_budget_events(d)] == states


def test_evaluate_and_record_appends_durably(tmp_path):
    store = obs_series.SeriesStore(str(tmp_path))
    store.ingest_events([
        _snap(T0 - 700.0, role="prober", pid=5,
              counters={"probe_attempts": 0.0, "probe_failures": 0.0}),
        _snap(T0 - 100.0, role="prober", pid=5,
              counters={"probe_attempts": 10.0,
                        "probe_failures": 10.0})])
    store.close()
    v = slomod.evaluate_and_record(str(tmp_path), "probe_errors@99/1d",
                                   now=T0)
    assert v["ok"] is False
    assert [e["state"] for e in v["events_appended"]] in \
        (["burning"], ["exhausted"])
    assert slomod.read_budget_events(str(tmp_path))


# ---------------------------------------------------------------------------
# Gauge-kind SLO inputs through the snapshot-rebuilt exposition
# ---------------------------------------------------------------------------

def test_prometheus_from_snapshot_gauge_byte_identity(fresh_metrics):
    """The changefeed_lag budget leg reads gauges from spool snapshots:
    the rebuilt exposition must be byte-identical to the scrape the
    live process would have served, including gauge float formatting."""
    obs_metrics.gauge("serve_changefeed_lag_seconds").set(0.25)
    obs_metrics.gauge("queue_drain_eta_seconds").set(1234.5)
    obs_metrics.gauge("stream_chips").set(0)
    snap = obs_metrics.get_registry().snapshot()
    text = obs_metrics.prometheus_from_snapshot(snap)
    assert text == obs_metrics.get_registry().prometheus()
    assert "firebird_serve_changefeed_lag_seconds 0.25" in text
    for line in text.splitlines():
        assert obs_metrics.PROM_LINE_RE.match(line), line


# ---------------------------------------------------------------------------
# Ops endpoints: /metrics/history and the /slo budgets block
# ---------------------------------------------------------------------------

@pytest.fixture
def ops_env(tmp_path, monkeypatch):
    """A file-backed telemetry home for Config.from_env(): one spool
    with historic snapshots plus the series dir next to it."""
    _write_spool(str(tmp_path), "worker", 42, [
        _snap(T0 + 5.0, counters={"scenes_seen": 3.0}),
        _snap(T0 + 25.0, counters={"scenes_seen": 8.0})])
    monkeypatch.setenv("FIREBIRD_SERIES_DIR", str(tmp_path / "series"))
    monkeypatch.setenv("FIREBIRD_TELEMETRY_DIR", str(tmp_path))
    return tmp_path


def _get(port, path):
    r = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                               timeout=5)
    return json.loads(r.read())


def test_history_and_slo_budget_endpoints(ops_env, fresh_metrics):
    from firebird_tpu.obs import server as obs_server

    status = obs_server.set_status(obs_server.RunStatus(
        "r", "test", slo_spec="batch_p95=30"))
    try:
        srv = obs_server.start_ops_server(0, status, host="127.0.0.1")
        try:
            big = int(time.time() - T0 + 3600)
            doc = _get(srv.port, f"/metrics/history?window={big}")
            assert doc["schema"] == "firebird-metric-history/1"
            assert doc["sources"] == ["worker:42"]
            assert [p["b"] for p in doc["points"]] == \
                [int((T0 + 5.0) // 10), int((T0 + 25.0) // 10)]
            # ?metric= filters the payload to one instrument
            doc = _get(srv.port,
                       f"/metrics/history?window={big}"
                       "&metric=scenes_seen")
            assert all(list(p["m"]["counters"]) == ["scenes_seen"]
                       for p in doc["points"])
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, "/metrics/history?res=7")
            assert ei.value.code == 400
            # /slo carries the budget block (stamps are historic: every
            # budget is no_data, which is ok=True, not a violation)
            doc = _get(srv.port, "/slo")
            assert doc["ok"] is True and doc["budgets"]["ok"] is True
            assert {b["ok"] for b in doc["budgets"]["budgets"]} == {None}
            # ?budgets=0 skips the disk walk
            assert "budgets" not in _get(srv.port, "/slo?budgets=0")
        finally:
            srv.close()
    finally:
        obs_server.clear_status()
    # the endpoint's ingestion persisted: a later reader sees the points
    assert obs_series.read_points(str(ops_env / "series"), 10)


def test_ops_server_shares_one_series_store(tmp_path, monkeypatch):
    """The threaded handlers use ONE process-wide SeriesStore:
    per-request instances share this pid, so two concurrent /slo or
    /metrics/history requests would append to the same segment files
    from two uncoordinated writers.  The cache re-keys (closing the
    old store) when the ambient config changes."""
    from firebird_tpu.obs import server as obs_server

    monkeypatch.setenv("FIREBIRD_SERIES_DIR", str(tmp_path / "series"))
    monkeypatch.setenv("FIREBIRD_TELEMETRY_DIR", str(tmp_path))
    s1 = obs_server._shared_store(Config.from_env())
    s2 = obs_server._shared_store(Config.from_env())
    assert s1 is not None and s1 is s2
    monkeypatch.setenv("FIREBIRD_SERIES", "0")
    assert obs_server._shared_store(Config.from_env()) is None


def test_history_endpoint_disabled_without_series(tmp_path, monkeypatch,
                                                  fresh_metrics):
    from firebird_tpu.obs import server as obs_server

    monkeypatch.setenv("FIREBIRD_SERIES_DIR", str(tmp_path / "series"))
    monkeypatch.setenv("FIREBIRD_SERIES", "0")
    status = obs_server.set_status(obs_server.RunStatus("r", "test"))
    try:
        srv = obs_server.start_ops_server(0, status, host="127.0.0.1")
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, "/metrics/history")
            assert ei.value.code == 503
        finally:
            srv.close()
    finally:
        obs_server.clear_status()
    assert not (tmp_path / "series").exists()


# ---------------------------------------------------------------------------
# firebird slo: CI-able exit codes
# ---------------------------------------------------------------------------

def test_slo_cli_exit_codes(tmp_path):
    from click.testing import CliRunner

    from firebird_tpu import cli

    env = {"FIREBIRD_SERIES_DIR": str(tmp_path / "series"),
           "FIREBIRD_TELEMETRY_DIR": str(tmp_path)}
    # disabled store: exit 2
    res = CliRunner().invoke(cli.entrypoint, ["slo"],
                             env=dict(env, FIREBIRD_TELEMETRY="0"))
    assert res.exit_code == 2 and json.loads(res.output)["disabled"]
    # no data: ok (exit 0), every budget no_data
    res = CliRunner().invoke(cli.entrypoint, ["slo"], env=env)
    assert res.exit_code == 0, res.output
    doc = json.loads(res.output)
    assert doc["ok"] is True and {b["ok"] for b in doc["budgets"]} == \
        {None}
    # a burning canary in fresh (reader-clock-now) spools: exit 1, and
    # the transition lands in the durable event log
    now = time.time()
    _write_spool(str(tmp_path), "prober", 5, [
        _snap(now - 60.0, role="prober", pid=5,
              counters={"probe_attempts": 1.0, "probe_failures": 1.0}),
        _snap(now - 11.0, role="prober", pid=5,
              counters={"probe_attempts": 20.0,
                        "probe_failures": 20.0})])
    res = CliRunner().invoke(
        cli.entrypoint,
        ["slo", "-b", "probe_errors@99/5m", "--fast", "45",
         "--slow", "90"], env=env)
    assert res.exit_code == 1, res.output
    doc = json.loads(res.output)
    (b,) = doc["budgets"]
    assert b["ok"] is False and (b["burning"] or b["exhausted"])
    assert doc["events_appended"]
    assert slomod.read_budget_events(str(tmp_path / "series"))
    # --no-record is a pure read: same verdict, no new events
    n = len(slomod.read_budget_events(str(tmp_path / "series")))
    res = CliRunner().invoke(
        cli.entrypoint,
        ["slo", "-b", "probe_errors@99/5m", "--fast", "45",
         "--slow", "90", "--no-record"], env=env)
    assert res.exit_code == 1
    assert len(slomod.read_budget_events(str(tmp_path / "series"))) == n


# ---------------------------------------------------------------------------
# The canary prober's units
# ---------------------------------------------------------------------------

def test_sparkline_rendering():
    from firebird_tpu.cli import _SPARK_GLYPHS, _sparkline

    assert _sparkline([]) == ""
    assert _sparkline([0.0, 0.0]) == _SPARK_GLYPHS[0] * 2
    s = _sparkline([0.0, 4.0, 8.0])
    assert s[0] == _SPARK_GLYPHS[0] and s[-1] == _SPARK_GLYPHS[-1]
    assert len(_sparkline(range(30))) == 30


def test_prober_refuses_bad_configs(tmp_path):
    from firebird_tpu.obs import prober as obs_prober

    with pytest.raises(ValueError, match="at least one surface"):
        obs_prober.CanaryProber(Config())
    with pytest.raises(ValueError, match="-x/-y"):
        obs_prober.CanaryProber(Config(), landing=str(tmp_path))
    with pytest.raises(ValueError, match="refuses to arm"):
        obs_prober.CanaryProber(Config(probe_sec=0),
                                serve_url="http://127.0.0.1:1")
    # an explicit interval overrides the knob-off default
    p = obs_prober.CanaryProber(Config(probe_sec=0),
                                serve_url="http://127.0.0.1:1",
                                interval=5.0)
    assert p.interval == 5.0


def test_webhook_sink_records_first_receipt():
    from firebird_tpu.obs import prober as obs_prober

    sink = obs_prober._WebhookSink()
    try:
        body = json.dumps({"schema": "firebird-alert-webhook/1",
                           "cursor": 3,
                           "alerts": [{"cx": 100, "cy": 200}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{sink.port}/probe", data=body,
            method="POST")
        t0 = time.time()
        assert urllib.request.urlopen(req, timeout=5).status == 200
        t = sink.first_receipt((100, 200), after=t0 - 1.0)
        assert t is not None and t >= t0 - 1.0
        # only receipts after the probe's append count
        assert sink.first_receipt((100, 200), time.time() + 60) is None
        assert sink.first_receipt((1, 2), 0.0) is None
        # a second delivery of the same chip keeps the FIRST receipt
        urllib.request.urlopen(req, timeout=5)
        assert sink.first_receipt((100, 200), after=0.0) == t
    finally:
        sink.close()


def test_sse_watcher_parses_alert_events():
    from firebird_tpu.obs import prober as obs_prober

    w = obs_prober._SSEWatcher("http://127.0.0.1:1", timeout=1.0)
    frames = [b": keepalive\n", b"\n",
              b"event: alert\n",
              b'data: {"cx": 100, "cy": 200, "date": 730000}\n',
              b"id: 17\n", b"\n",
              b"event: other\n", b'data: {"cx": 1, "cy": 2}\n', b"\n",
              b"event: alert\n", b"data: not-json\n", b"\n"]
    w._consume(iter(frames))
    assert w.first_seen((100, 200), after=0.0) is not None
    assert w.first_seen((1, 2), after=0.0) is None     # non-alert event
    assert w.cursor == 17                              # reconnect point


def test_resolve_pending_times_out_as_failure(fresh_metrics):
    from firebird_tpu.obs import prober as obs_prober

    p = obs_prober.CanaryProber(Config(), serve_url="http://127.0.0.1:1")
    p.pending.append({"kind": "alert", "cid": (1, 2),
                      "t_appended": time.time() - 999.0,
                      "deadline": 10.0})
    p.pending.append({"kind": "alert", "cid": (3, 4),
                      "t_appended": time.time(), "deadline": 999.0})
    p._resolve_pending()
    assert obs_metrics.counter("probe_failures_alert").value == 1
    assert obs_metrics.counter("probe_attempts_alert").value == 1
    assert len(p.pending) == 1             # the fresh one still waits


def test_alert_conveyor_confirms_on_sixth_scene(tmp_path):
    """The staged conveyor: one scene per tick per in-flight chip, the
    SCENES_TO_CONFIRM-th append is the end-to-end attempt, scenes are
    bbox'd strictly inside their chip's cell."""
    from firebird_tpu.ingest.sources import FileSource
    from firebird_tpu.obs import prober as obs_prober

    c = obs_prober._AlertConveyor(str(tmp_path), 100.0, 200.0,
                                  chip_offset=0, chips=1)
    (cid,) = c.reserve
    sx, sy = c.span
    x0, y0, x1, y1 = c._bbox(cid)
    assert cid[0] < x0 < x1 < cid[0] + sx
    assert cid[1] - sy < y0 < y1 < cid[1]
    confirmed = []
    for _ in range(obs_prober.SCENES_TO_CONFIRM):
        assert not c.exhausted()
        confirmed += c.tick()
    assert [a["cid"] for a in confirmed] == [cid]
    assert confirmed[0]["scene_id"] == \
        f"PROBE_{cid[0]}_{cid[1]}_{obs_prober.SCENES_TO_CONFIRM - 1}"
    assert c.exhausted() and c.tick() == []
    # the landing zone carries one scene per stage, each bbox'd
    scenes = FileSource(str(tmp_path)).list_acquisitions()
    probe = [s for s in scenes
             if s["scene_id"].startswith("PROBE_")]
    assert len(probe) == obs_prober.SCENES_TO_CONFIRM
    assert all(s.get("bbox") for s in probe)


# ---------------------------------------------------------------------------
# firebird-lint: SLO objective specs vs the metric registry
# ---------------------------------------------------------------------------

SLO_LINT_BASE = """
    OBJECTIVES = {
        "good_p95": ("histogram", "thing_seconds", "p95", "fine"),
        "pair": ("ratio", ("thing_bad", "thing_seconds"), None, "r"),
        "live": ("watchdog", "last_beat_age_sec", None, "skipped"),
    }
    DEFAULT_SPEC = "good_p95=30"
    DEFAULT_BUDGET_SPEC = "good_p95<30@99/7d"
"""

SLO_LINT_SITE = """
    from firebird_tpu.obs.metrics import histogram

    def f():
        histogram("thing_seconds", help="h").observe(1.0)
"""


def test_lint_slo_objectives_clean(tmp_path):
    from tests.test_lint import build_repo, rules_hit

    from firebird_tpu.analysis import run_lint

    root = build_repo(tmp_path, {
        "firebird_tpu/obs/slo.py": SLO_LINT_BASE.replace(
            '"thing_bad", ', '"thing_seconds", '),
        "firebird_tpu/work.py": SLO_LINT_SITE})
    res = run_lint(root)
    assert "slo-metric-unknown" not in rules_hit(res)
    assert "slo-spec-unknown" not in rules_hit(res)


def test_lint_slo_metric_and_spec_unknown(tmp_path):
    from tests.test_lint import build_repo, by_rule

    from firebird_tpu.analysis import run_lint

    root = build_repo(tmp_path, {
        "firebird_tpu/obs/slo.py": SLO_LINT_BASE.replace(
            'DEFAULT_SPEC = "good_p95=30"',
            'DEFAULT_SPEC = "ghost_p99=30"'),
        "firebird_tpu/work.py": SLO_LINT_SITE})
    res = run_lint(root)
    # the ratio's numerator has no registration site anywhere
    unknown = by_rule(res, "slo-metric-unknown")
    assert len(unknown) == 1 and "thing_bad" in unknown[0].message
    spec = by_rule(res, "slo-spec-unknown")
    assert len(spec) == 1 and "ghost_p99" in spec[0].message
    # the watchdog objective is exempt: its field is a report-block
    # key, not a registry instrument
    assert not any("last_beat_age_sec" in f.message for f in unknown)


def test_lint_slo_metric_known_via_catalog(tmp_path):
    """A metric with no live call site but a METRIC_HELP entry is still
    known — catalog names are registry names (dynamic call sites)."""
    from tests.test_lint import build_repo, rules_hit

    from firebird_tpu.analysis import run_lint

    root = build_repo(tmp_path, {
        "firebird_tpu/obs/slo.py": SLO_LINT_BASE,
        "firebird_tpu/obs/metrics.py": """
            METRIC_HELP = {
                "thing_bad": "bad things",
            }
        """,
        "firebird_tpu/work.py": SLO_LINT_SITE})
    res = run_lint(root)
    assert "slo-metric-unknown" not in rules_hit(res)
