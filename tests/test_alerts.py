"""Near-real-time alerting: log, feed, webhooks, SSE, repair (docs/ALERTS.md).

Pure-unit coverage of the alerting loop's parts; the streaming driver's
end-to-end emission rides the existing stream-driver fixture
(test_stream_driver.py) and the chaos proof is `make alert-smoke`.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from firebird_tpu.alerts.feed import AlertFeed, WebhookDeliverer, parse_bbox
from firebird_tpu.alerts.log import AlertLog
from firebird_tpu.config import Config
from firebird_tpu.utils import dates as dt


def rec(px, py, day, **kw):
    return dict({"cx": 100, "cy": 200, "px": px, "py": py,
                 "break_day": float(day)}, **kw)


@pytest.fixture
def alog(tmp_path):
    al = AlertLog(str(tmp_path / "alerts.db"))
    yield al
    al.close()


# ---------------------------------------------------------------------------
# the durable log
# ---------------------------------------------------------------------------

def test_append_dedupe_and_cursor(alog):
    ins, dup = alog.append([rec(1, 2, 728000), rec(3, 4, 728000)],
                           run_id="r1")
    assert (ins, dup) == (2, 0)
    # re-delivery of the same logical alerts: exactly-once
    ins, dup = alog.append([rec(1, 2, 728000), rec(5, 6, 728016)])
    assert (ins, dup) == (1, 1)
    assert alog.count() == 3
    # cursor resume: strictly increasing ids (gaps allowed — a deduped
    # insert may burn a rowid), no misses, no re-reads
    page = alog.since(0, limit=2)
    assert len(page) == 2 and page[0]["id"] < page[1]["id"]
    rest = alog.since(page[-1]["id"])
    assert len(rest) == 1 and rest[0]["id"] > page[-1]["id"]
    assert rest[0]["id"] == alog.latest_cursor()
    assert alog.since(alog.latest_cursor()) == []


def test_rebreak_same_pixel_new_day_is_new_alert(alog):
    """The satellite edge: a repaired pixel whose tail breaks AGAIN
    must emit a second alert under the new break_day — dedup is on
    (pixel, break_day), not on pixel."""
    assert alog.append([rec(7, 8, 728000)]) == (1, 0)
    # repair lands, tail breaks again later: NEW key, second alert
    assert alog.append([rec(7, 8, 728200)]) == (1, 0)
    # the original day stays a duplicate forever
    assert alog.append([rec(7, 8, 728000)]) == (0, 1)
    days = [r["break_day"] for r in alog.since(0)
            if (r["px"], r["py"]) == (7, 8)]
    assert days == [728000.0, 728200.0]


def test_since_filters(alog):
    alog.append([rec(100, 200, dt.to_ordinal("1999-06-01")),
                 rec(130, 170, dt.to_ordinal("2000-06-01")),
                 rec(900, 900, dt.to_ordinal("1999-06-01"))])
    got = alog.since(0, bbox=(90, 150, 150, 210))
    assert {(r["px"], r["py"]) for r in got} == {(100, 200), (130, 170)}
    got = alog.since(0, t0="2000-01-01")
    assert [r["px"] for r in got] == [130]
    got = alog.since(0, t1="1999-12-31")
    assert {r["px"] for r in got} == {100, 900}
    assert got[0]["break_date"] == "1999-06-01"


def test_subscribers_idempotent_and_monotonic(alog):
    sid = alog.subscribe("http://h/hook")
    assert alog.subscribe("http://h/hook") == sid   # idempotent on url
    alog.append([rec(1, 1, 1000), rec(2, 2, 1000)])
    assert alog.subscribers()[0]["lag"] == 2
    alog.advance(sid, 2)
    assert alog.subscribers()[0] == dict(alog.subscribers()[0], cursor=2,
                                         lag=0)
    alog.advance(sid, 1)                            # rewind rejected
    assert alog.subscribers()[0]["cursor"] == 2
    with pytest.raises(ValueError):
        alog.subscribe("not-a-url")


def test_status_and_parse_bbox(alog):
    alog.append([rec(1, 1, 1000)])
    alog.subscribe("http://h/hook")
    s = alog.status()
    assert s["depth"] == 1 and s["latest_cursor"] == 1
    assert s["subscribers"][0]["lag"] == 1
    assert parse_bbox("1,2,3.5,4") == (1.0, 2.0, 3.5, 4.0)
    with pytest.raises(ValueError):
        parse_bbox("1,2,3")


# ---------------------------------------------------------------------------
# webhook delivery: durable cursor, retries, catch-up
# ---------------------------------------------------------------------------

def test_webhook_delivery_cursor_catchup(alog):
    cfg = Config(store_backend="memory")
    alog.append([rec(i, i, 1000 + i) for i in range(10)])
    sid = alog.subscribe("http://h/hook")
    got = []

    def post(url, body, timeout):
        got.append(json.loads(body))
        return 200

    d1 = WebhookDeliverer(alog, cfg, post=post, sleep=lambda s: None)
    assert d1.deliver_once(batch=4, max_batches=1) == 4   # partial, "dies"
    assert alog.subscribers()[0]["cursor"] == 4           # durable
    # a fresh incarnation resumes from the cursor: remainder only
    d2 = WebhookDeliverer(alog, cfg, post=post, sleep=lambda s: None)
    assert d2.deliver_once(batch=4) == 6
    ids = [a["id"] for doc in got for a in doc["alerts"]]
    assert ids == list(range(1, 11))                      # exactly once
    assert alog.subscribers()[0]["lag"] == 0
    # new alerts after catch-up deliver incrementally
    alog.append([rec(99, 99, 2000)])
    assert d2.deliver_once() == 1
    assert got[-1]["alerts"][0]["px"] == 99
    assert sid == 1


def test_webhook_failure_holds_cursor(alog):
    cfg = Config(store_backend="memory", fetch_retries=1)
    alog.append([rec(i, i, 1000 + i) for i in range(3)])
    alog.subscribe("http://dead/hook")
    calls = []

    def post(url, body, timeout):
        calls.append(url)
        raise OSError("connection refused")

    d = WebhookDeliverer(alog, cfg, post=post, sleep=lambda s: None)
    assert d.deliver_once() == 0
    assert len(calls) == 2                  # 1 + fetch_retries attempts
    sub = alog.subscribers()[0]
    assert sub["cursor"] == 0 and sub["failures"] == 1
    # receiver heals: the held batch redelivers in full
    d._post = lambda url, body, timeout: 200
    assert d.deliver_once() == 3
    assert alog.subscribers()[0]["lag"] == 0


# ---------------------------------------------------------------------------
# the serve mount: pull, SSE, webhook registration
# ---------------------------------------------------------------------------

@pytest.fixture
def served(tmp_path):
    from firebird_tpu.serve import api as serve_api
    from firebird_tpu.store import open_store

    cfg = Config(store_backend="memory", serve_deadline_sec=5.0)
    store = open_store("memory", "", cfg.keyspace())
    alog = AlertLog(str(tmp_path / "alerts.db"))
    alog.append([rec(100 + i, 200 - i, 728000 + 16 * i, score=1.0,
                     magnitude=2.5) for i in range(5)], run_id="t")
    service = serve_api.ServeService(store, cfg,
                                     alerts=AlertFeed(alog, cfg))
    srv = serve_api.start_serve_server(0, service, host="127.0.0.1")
    yield f"http://127.0.0.1:{srv.port}", alog
    srv.close()
    alog.close()
    store.close()


def _get(url):
    r = urllib.request.urlopen(url, timeout=10)
    return r.status, json.loads(r.read())


def test_alerts_pull_endpoint(served):
    base, _ = served
    code, doc = _get(base + "/v1/alerts?since=0")
    assert code == 200 and len(doc["alerts"]) == 5
    assert doc["cursor"] == doc["latest"] == 5
    a = doc["alerts"][0]
    assert a["px"] == 100 and a["break_date"] == dt.to_iso(728000)
    # cursor paging
    code, doc = _get(base + "/v1/alerts?since=3")
    assert [r["id"] for r in doc["alerts"]] == [4, 5]
    # bbox + time filters are servable
    code, doc = _get(base + "/v1/alerts?since=0&bbox=100,199,101,200")
    assert {r["px"] for r in doc["alerts"]} == {100, 101}
    code, doc = _get(base + "/v1/alerts?since=0&t1="
                     + dt.to_iso(728000 + 16))
    assert len(doc["alerts"]) == 2
    # malformed bbox / dates are a 400, not a 500 (and on the SSE path
    # a bad date must be rejected BEFORE stream headers go out)
    for bad in ("/v1/alerts?since=0&bbox=1,2",
                "/v1/alerts?since=0&t0=garbage",
                "/v1/alerts/stream?since=0&t1=garbage"):
        try:
            urllib.request.urlopen(base + bad, timeout=10)
            assert False, f"expected 400 for {bad}"
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_alerts_404_without_log(tmp_path):
    from firebird_tpu.serve import api as serve_api
    from firebird_tpu.store import open_store

    cfg = Config(store_backend="memory")
    store = open_store("memory", "", cfg.keyspace())
    service = serve_api.ServeService(store, cfg)     # alerts=None
    srv = serve_api.start_serve_server(0, service, host="127.0.0.1")
    try:
        for path in ("/v1/alerts?since=0", "/v1/alerts/webhooks"):
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=10)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
    finally:
        srv.close()
        store.close()


def test_webhook_registration_endpoint(served):
    base, alog = served
    req = urllib.request.Request(
        base + "/v1/alerts/webhooks?url=http://h/hook&since=2",
        method="POST")
    code, doc = (lambda r: (r.status, json.loads(r.read())))(
        urllib.request.urlopen(req, timeout=10))
    assert code == 200 and doc["latest"] == 5
    # idempotent re-registration keeps the durable cursor
    urllib.request.urlopen(urllib.request.Request(
        base + "/v1/alerts/webhooks?url=http://h/hook", method="POST"),
        timeout=10)
    code, doc = _get(base + "/v1/alerts/webhooks")
    assert len(doc["subscribers"]) == 1
    assert doc["subscribers"][0]["cursor"] == 2
    assert doc["subscribers"][0]["lag"] == 3


def test_sse_stream_replay_and_live(served):
    base, alog = served
    r = urllib.request.urlopen(base + "/v1/alerts/stream?since=0",
                               timeout=10)
    assert r.headers["Content-Type"] == "text/event-stream"
    events, ids = [], []
    # live append mid-session from another thread
    threading.Timer(0.1, lambda: alog.append([rec(999, 999, 730000)])).start()
    while len(events) < 6:
        line = r.readline()
        assert line, "server closed before all events arrived"
        if line.startswith(b"data:"):
            events.append(json.loads(line[5:].strip()))
        elif line.startswith(b"id:"):
            ids.append(int(line[3:].strip()))
    r.close()
    assert [e["id"] for e in events] == [1, 2, 3, 4, 5, 6]
    assert ids == [1, 2, 3, 4, 5, 6]       # SSE id: == cursor, resumable
    assert events[-1]["px"] == 999         # the live one arrived too
    # resume from the last seen cursor: only what follows
    r = urllib.request.urlopen(base + "/v1/alerts/stream?since=5",
                               timeout=10)
    line = b""
    while not line.startswith(b"data:"):
        line = r.readline()
    r.close()
    assert json.loads(line[5:].strip())["id"] == 6


# ---------------------------------------------------------------------------
# repair scheduling: at most one open job per chip
# ---------------------------------------------------------------------------

def test_enqueue_repairs_idempotent(tmp_path):
    from firebird_tpu.fleet.plan import enqueue_repairs
    from firebird_tpu.fleet.queue import FleetQueue

    q = FleetQueue(str(tmp_path / "fleet.db"))
    try:
        ids = enqueue_repairs(q, {(100, 200): 50, (400, 200): 7},
                              acquired="1995-01-01/2000-12-31")
        assert len(ids) == 2
        job = q.job(ids[0])
        assert job["job_type"] == "repair" and job["payload"]["pixels"] == 50
        # the same debt re-rolled: both chips have OPEN jobs -> no dupes
        assert enqueue_repairs(q, {(100, 200): 50, (400, 200): 7},
                               acquired="x") == []
        # a LEASED job still counts as open
        lease = q.claim("w1")
        assert enqueue_repairs(q, {(lease.payload["cx"],
                                    lease.payload["cy"]): 50},
                               acquired="x") == []
        # once the repair lands, a NEW break may re-enqueue the chip
        q.ack(lease)
        again = enqueue_repairs(
            q, {(lease.payload["cx"], lease.payload["cy"]): 3},
            acquired="x")
        assert len(again) == 1
        assert q.open_jobs("repair") != {}
    finally:
        q.close()


def test_schedule_repairs_memory_backend_degrades(tmp_path):
    from firebird_tpu.alerts.repair import schedule_repairs

    cfg = Config(store_backend="memory")      # no queue location
    assert schedule_repairs(cfg, {(1, 2): 3},
                            acquired="1995-01-01/2000-12-31") == []
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "s.db"))
    jids = schedule_repairs(cfg, {(1, 2): 3},
                            acquired="1995-01-01/2000-12-31")
    assert len(jids) == 1
    assert schedule_repairs(cfg, {(1, 2): 3}, acquired="x") == []


# ---------------------------------------------------------------------------
# incremental re-break: two breaks, two distinct alert keys
# ---------------------------------------------------------------------------

def test_incremental_rebreak_emits_second_key(alog):
    import jax.numpy as jnp

    from firebird_tpu.ccd import incremental, params

    P, B = 1, 7

    def fresh_state():
        return incremental.StreamState(
            coefs=jnp.zeros((P, B, 8), jnp.float32),
            rmse=jnp.ones((P, B), jnp.float32),
            vario=jnp.ones((P, B), jnp.float32),
            nobs=jnp.full(P, 20, jnp.int32),
            n_exceed=jnp.zeros(P, jnp.int32),
            end_day=jnp.full(P, 727990.0, jnp.float32),
            exceed_day0=jnp.zeros(P, jnp.float32),
            break_day=jnp.zeros(P, jnp.float32),
            active=jnp.ones(P, bool))

    def drive_to_break(st, day0):
        for k in range(params.PEEK_SIZE):
            day = day0 + 16 * k
            st = incremental.step(
                st, jnp.asarray(incremental.design_row(day, 727000.0)),
                jnp.full((P, B), 5000.0, jnp.float32),
                jnp.full(P, 1 << params.QA_CLEAR_BIT, jnp.int32),
                float(day))
        return st

    st = drive_to_break(fresh_state(), 728000.0)
    b1 = float(np.asarray(st.break_day)[0])
    assert b1 == 728000.0                    # dated at the first exceed
    assert alog.append([rec(10, 20, b1)]) == (1, 0)
    # repair reseeds the state (break_day cleared), the tail breaks
    # again LATER: a new break_day, a new alert — not swallowed by dedup
    st2 = drive_to_break(fresh_state(), 728300.0)
    b2 = float(np.asarray(st2.break_day)[0])
    assert b2 == 728300.0 and b2 != b1
    assert alog.append([rec(10, 20, b2)]) == (1, 0)
    # while a re-emission of either break stays exactly-once
    assert alog.append([rec(10, 20, b1), rec(10, 20, b2)]) == (0, 2)


# ---------------------------------------------------------------------------
# the freshness SLO leg
# ---------------------------------------------------------------------------

def test_alert_freshness_objective():
    from firebird_tpu.obs import slo as slomod

    metrics = {"histograms": {"alert_visible_seconds":
                              {"count": 4, "p95": 12.5}}}
    out = slomod.evaluate_snapshot(metrics, spec="alert_freshness=60")
    (obj,) = out["objectives"]
    assert obj["name"] == "alert_freshness" and obj["ok"] is True
    assert obj["value_sec"] == 12.5
    out = slomod.evaluate_snapshot(metrics, spec="alert_freshness=5")
    assert out["ok"] is False and out["violations"] == 1
    # default spec carries the leg; no data neither passes nor fails
    out = slomod.evaluate_snapshot({"histograms": {}})
    by = {o["name"]: o for o in out["objectives"]}
    assert by["alert_freshness"]["ok"] is None
    assert Config(slo="alert_freshness=30").slo    # validates at construction


# ---------------------------------------------------------------------------
# operator surface: firebird status alerts view
# ---------------------------------------------------------------------------

def test_status_alerts_view(tmp_path):
    from click.testing import CliRunner

    from firebird_tpu import cli
    from firebird_tpu.alerts.log import alert_db_path
    from firebird_tpu.fleet.plan import enqueue_repairs
    from firebird_tpu.fleet.queue import FleetQueue

    env = {"FIREBIRD_STORE_BACKEND": "sqlite",
           "FIREBIRD_STORE_PATH": str(tmp_path / "s.db")}
    cfg = Config.from_env(env=env)
    # seed a store file, an alert log with a lagging subscriber, and an
    # open repair job on the fleet queue next to it
    from firebird_tpu.store import open_store

    open_store("sqlite", cfg.store_path, cfg.keyspace()).close()
    al = AlertLog(alert_db_path(cfg))
    al.append([rec(1, 1, 728000), rec(2, 2, 728000)])
    al.subscribe("http://h/hook")
    al.close()
    q = FleetQueue(str(tmp_path / "fleet.db"))
    enqueue_repairs(q, {(100, 200): 9}, acquired="a")
    q.close()
    env["FIREBIRD_FLEET_DB"] = str(tmp_path / "fleet.db")
    res = CliRunner().invoke(cli.entrypoint, ["status"], env=env)
    assert res.exit_code == 0, res.output
    out = json.loads(res.output)
    assert out["alerts"]["depth"] == 2
    assert out["alerts"]["latest_cursor"] == 2
    assert out["alerts"]["subscribers"][0]["lag"] == 2
    assert out["alerts"]["open_repair_jobs"] == 1

    # a corrupt alert db degrades the section, not the command
    with open(alert_db_path(cfg), "wb") as f:
        f.write(b"not a database")
    res = CliRunner().invoke(cli.entrypoint, ["status"], env=env)
    assert res.exit_code == 0, res.output
    out = json.loads(res.output)
    assert "error" in out["alerts"]
    assert out["tables"] is not None       # the store view survived
