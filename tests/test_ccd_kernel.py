"""Kernel-vs-oracle parity tests (CPU, float64).

The kernel must reproduce the NumPy oracle decision-for-decision: same
segment counts, same start/end/break days, same processing masks, and
numerically close models.  Runs on small pixel/time slices so CI stays
fast; full-chip parity is exercised by bench/verification runs.
"""

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from firebird_tpu.ccd import detect, kernel, params, synthetic
from firebird_tpu.ingest import SyntheticSource, pack, pixel_timeseries
from firebird_tpu.ingest.packer import PackedChips


def slice_pixels(p: PackedChips, pix: np.ndarray) -> PackedChips:
    """A PackedChips restricted to selected pixels (keeps chip axis)."""
    return PackedChips(cids=p.cids, dates=p.dates,
                       spectra=p.spectra[:, :, pix, :],
                       qas=p.qas[:, pix, :], n_obs=p.n_obs)


@pytest.fixture(scope="module")
def packed():
    src = SyntheticSource(seed=5, start="1995-01-01", end="2001-01-01",
                          cloud_frac=0.1)
    p = pack([src.chip(100, 200)], bucket=64)
    # 60 pixels: a stretch of the chip guaranteed to include change-patch
    # and stable pixels (patch is a 50x50 block somewhere).
    rng = np.random.default_rng(0)
    pix = rng.choice(10000, size=60, replace=False)
    return slice_pixels(p, pix), p, pix


def fetch(seg: kernel.ChipSegments, chip: int = 0) -> kernel.ChipSegments:
    # None-valued optionals (e.g. lanes_migrated on non-rebalancing
    # dispatches) pass through, matching kernel.chip_slice's contract.
    return kernel.ChipSegments(*[
        None if getattr(seg, f.name) is None
        else np.asarray(getattr(seg, f.name)[chip])
        for f in dataclasses.fields(seg)])


def run_kernel(p: PackedChips) -> kernel.ChipSegments:
    return fetch(kernel.detect_packed(p, dtype=jnp.float64))


def test_round_counts_sane(packed):
    """The phase-gate counters (ChipSegments.round_counts): the INIT gate
    opens at least once (round 1) but far fewer times than the round
    count (steady-state rounds are pure monitor), the fit gate at least
    once, and no gate exceeds the round total."""
    small, _, _ = packed
    seg = run_kernel(small)
    rounds = int(seg.rounds)
    ir, fr, cr = (int(x) for x in seg.round_counts)
    assert 1 <= ir <= rounds
    assert 1 <= fr <= rounds
    assert 0 <= cr <= rounds
    # the gating premise: most rounds skip INIT
    assert ir < rounds / 2


def test_structural_parity(packed):
    small, full, pix = packed
    seg = run_kernel(small)
    dates = small.dates[0][: int(small.n_obs[0])]
    n_two = 0
    for i in range(len(pix)):
        o = detect(**pixel_timeseries(small, 0, i))
        k = kernel.segments_to_records(seg, dates, i)
        assert len(o["change_models"]) == len(k["change_models"]), i
        n_two += len(o["change_models"]) > 1
        for om, km in zip(o["change_models"], k["change_models"]):
            assert om["start_day"] == km["start_day"], i
            assert om["end_day"] == km["end_day"], i
            assert om["break_day"] == km["break_day"], i
            assert om["curve_qa"] == km["curve_qa"], i
            assert om["observation_count"] == km["observation_count"], i
            assert om["change_probability"] == pytest.approx(
                km["change_probability"], abs=1e-6), i
        assert o["processing_mask"] == k["processing_mask"], i
    # the sample actually exercises break detection
    assert n_two >= 3


def test_numeric_parity(packed):
    small, _, pix = packed
    seg = run_kernel(small)
    dates = small.dates[0][: int(small.n_obs[0])]
    for i in range(0, len(pix), 7):
        o = detect(**pixel_timeseries(small, 0, i))
        k = kernel.segments_to_records(seg, dates, i)
        for om, km in zip(o["change_models"], k["change_models"]):
            for band in params.BAND_NAMES:
                assert km[band]["rmse"] == pytest.approx(om[band]["rmse"],
                                                         rel=1e-6, abs=1e-6)
                assert km[band]["intercept"] == pytest.approx(
                    om[band]["intercept"], rel=1e-5, abs=1e-3)
                assert km[band]["magnitude"] == pytest.approx(
                    om[band]["magnitude"], rel=1e-6, abs=1e-6)
                for a, b in zip(om[band]["coefficients"],
                                km[band]["coefficients"]):
                    assert b == pytest.approx(a, rel=1e-5, abs=1e-6)


def _pack_pixels(t, Ys, qas):
    """Pack a handful of hand-built pixels into a 1-chip batch."""
    P = len(Ys)
    T = t.shape[0]
    spectra = np.stack([np.asarray(Y, np.int16) for Y in Ys])  # [P,7,T]
    spectra = spectra.transpose(1, 0, 2)[None]                 # [1,7,P,T]
    qa = np.stack([np.asarray(q, np.uint16) for q in qas])[None]
    return PackedChips(cids=np.zeros((1, 2), np.int64),
                       dates=t[None].astype(np.int32),
                       spectra=spectra, qas=qa,
                       n_obs=np.array([T], np.int32))


def test_procedures_parity():
    rng = np.random.default_rng(44)
    t = synthetic.acquisition_dates("1995-01-01", "2000-01-01", 16)
    T = t.shape[0]
    Y = synthetic.harmonic_series(t, rng)
    qa_clear = np.full(T, synthetic.QA_CLEAR, np.uint16)
    qa_snow = np.full(T, synthetic.QA_SNOW, np.uint16)
    qa_snow[: T // 10] = synthetic.QA_CLEAR
    qa_cloud = np.full(T, synthetic.QA_CLOUD, np.uint16)
    qa_fill = np.full(T, synthetic.QA_FILL, np.uint16)
    Yf = np.full((7, T), params.FILL_VALUE, np.float64)

    p = _pack_pixels(t, [Y, Y, Y, Yf], [qa_clear, qa_snow, qa_cloud, qa_fill])
    seg = run_kernel(p)
    dates = p.dates[0]
    expected = ["standard", "permanent-snow", "insufficient-clear", "no-data"]
    for i, proc in enumerate(expected):
        o = detect(**pixel_timeseries(p, 0, i))
        k = kernel.segments_to_records(seg, dates, i)
        assert k["procedure"] == proc == o["procedure"]
        assert len(k["change_models"]) == len(o["change_models"])
        for om, km in zip(o["change_models"], k["change_models"]):
            assert om["start_day"] == km["start_day"]
            assert om["curve_qa"] == km["curve_qa"]
        assert k["processing_mask"] == o["processing_mask"]


def test_spike_outlier_parity():
    rng = np.random.default_rng(45)
    t = synthetic.acquisition_dates("1995-01-01", "2000-01-01", 16)
    Y = synthetic.harmonic_series(t, rng)
    Y[:, t.shape[0] // 2] += 3000.0
    qa = np.full(t.shape[0], synthetic.QA_CLEAR, np.uint16)
    p = _pack_pixels(t, [Y], [qa])
    seg = run_kernel(p)
    o = detect(**pixel_timeseries(p, 0, 0))
    k = kernel.segments_to_records(seg, p.dates[0], 0)
    assert o["processing_mask"] == k["processing_mask"]
    assert k["processing_mask"][t.shape[0] // 2] == 0


def test_padding_is_inert(packed):
    """Extra padded capacity must not change results."""
    small, _, pix = packed
    T = small.dates.shape[1]
    pad = 64
    bigger = PackedChips(
        cids=small.cids,
        dates=np.pad(small.dates, ((0, 0), (0, pad))),
        spectra=np.pad(small.spectra, ((0, 0), (0, 0), (0, 0), (0, pad)),
                       constant_values=params.FILL_VALUE),
        qas=np.pad(small.qas, ((0, 0), (0, 0), (0, pad)),
                   constant_values=int(synthetic.QA_FILL)),
        n_obs=small.n_obs)
    a = run_kernel(small)
    b = run_kernel(bigger)
    np.testing.assert_array_equal(a.n_segments, b.n_segments)
    np.testing.assert_allclose(a.seg_meta, b.seg_meta, rtol=1e-12)
    np.testing.assert_array_equal(a.mask, b.mask[:, :T])


def test_bitonic_sort_matches_numpy():
    """The sorting network behind the masked medians is bit-identical to
    a full sort for finite/inf data at every width class (power-of-two,
    odd, 1) and both dtypes."""
    rng = np.random.default_rng(5)
    for W in (1, 2, 3, 5, 8, 17, 24, 64, 100):
        for dt in (np.float32, np.float64):
            x = rng.normal(size=(40, W)).astype(dt)
            x[rng.random(x.shape) < 0.2] = np.inf        # masked slots
            got = np.asarray(kernel._bitonic_sort_last(jnp.asarray(x)))
            np.testing.assert_array_equal(got, np.sort(x, axis=-1))


def test_chol_solve_small_accuracy_and_degenerate_nan():
    """The unrolled SPD solve matches LAPACK on well-conditioned systems
    and returns NaN on numerically non-PD lanes (the flag-nothing
    degenerate contract of the Tmask screen)."""
    rng = np.random.default_rng(6)
    A = rng.normal(size=(50, 5, 5))
    G = np.einsum("pij,pkj->pik", A, A) + 1e-6 * np.eye(5)
    c = rng.normal(size=(50, 5))
    got = np.asarray(kernel._chol_solve_small(
        jnp.asarray(G.reshape(50, 25)), jnp.asarray(c)))
    want = np.linalg.solve(G, c[..., None])[..., 0]
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-8)
    # one lane made indefinite -> that lane (and only that lane) is NaN
    G_bad = G.copy()
    G_bad[7] = -np.eye(5)
    got = np.asarray(kernel._chol_solve_small(
        jnp.asarray(G_bad.reshape(50, 25)), jnp.asarray(c)))
    assert np.isnan(got[7]).all()
    ok = np.ones(50, bool)
    ok[7] = False
    assert np.isfinite(got[ok]).all()


def overflow_packed() -> PackedChips:
    """A 4-pixel chip whose pixels close 11+ segments (shared by the
    kernel-level and driver-level capacity-overflow tests)."""
    t = synthetic.acquisition_dates("1985-01-01", "2005-01-01", 8)
    rng = np.random.default_rng(12)
    Y = synthetic.harmonic_series(t, rng, noise=20.0)
    # one confirmed break per ~55 obs (440 days — enough for the 365-day
    # init window plus the 6-obs confirmation run)
    for k, c in enumerate(range(55, t.shape[0] - 55, 55)):
        Y[:, c:] += 900.0 * (1 if k % 2 == 0 else -1)
    px = synthetic.pixel(t, Y)
    spectra = np.stack([px[n] for n in params.BAND_NAMES_PLURAL])
    T = t.shape[0]
    Tb = -64 * (-T // 64)
    p = PackedChips(
        cids=np.zeros((1, 2), np.int64),
        dates=np.pad(t[None], ((0, 0), (0, Tb - T))).astype(np.int32),
        spectra=np.pad(spectra[None, :, None].repeat(4, 2),
                       ((0, 0), (0, 0), (0, 0), (0, Tb - T)),
                       constant_values=params.FILL_VALUE),
        qas=np.pad(px["qas"][None, None].repeat(4, 1),
                   ((0, 0), (0, 0), (0, Tb - T)),
                   constant_values=1 << params.QA_FILL_BIT),
        n_obs=np.array([T], np.int32))
    return p


def test_segment_capacity_overflow_redispatches():
    """A pixel that closes more than MAX_SEGMENTS segments must not crash
    or silently truncate: detect_packed re-dispatches at doubled capacity
    until every segment fits, and the result matches the (uncapped)
    oracle.  Found by fuzzing — a dense 20-year grid with a level shift
    every ~55 obs closes 11+ segments."""
    p = overflow_packed()
    t = p.dates[0][: int(p.n_obs[0])]
    seg = kernel.detect_packed(p, dtype=jnp.float64)
    o = detect(**pixel_timeseries(p, 0, 0))
    n_oracle = len(o["change_models"])
    assert n_oracle > kernel.MAX_SEGMENTS, "fixture must overflow capacity"
    one = kernel.chip_slice(seg, 0, to_host=True)
    assert int(one.n_segments[0]) == n_oracle
    assert one.seg_meta.shape[1] >= n_oracle       # buffer actually grew
    k = kernel.segments_to_records(one, t, 0)
    assert len(k["change_models"]) == n_oracle
    for om, km in zip(o["change_models"], k["change_models"]):
        assert om["break_day"] == km["break_day"]
        assert om["start_day"] == km["start_day"]
