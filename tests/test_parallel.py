"""Mesh sharding tests on the 8-device virtual CPU platform."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from firebird_tpu.ccd import kernel
from firebird_tpu.ingest import SyntheticSource, pack
from firebird_tpu.ingest.packer import PackedChips
from firebird_tpu.parallel import make_mesh, chip_sharding
from firebird_tpu.parallel.mesh import detect_sharded


@pytest.fixture(scope="module")
def packed8():
    src = SyntheticSource(seed=2, start="1995-01-01", end="1996-06-01")
    chips = [src.chip(3000 * i, 0) for i in range(8)]
    p = pack(chips, bucket=32)
    return PackedChips(cids=p.cids, dates=p.dates,
                       spectra=p.spectra[:, :, :128, :],
                       qas=p.qas[:, :128, :], n_obs=p.n_obs)


def test_mesh_creation():
    mesh = make_mesh(n_devices=8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("data",)


def test_sharded_detect_matches_unsharded(packed8):
    mesh = make_mesh(n_devices=8)
    seg_sh = detect_sharded(packed8, mesh, dtype=jnp.float64)
    seg = kernel.detect_packed(packed8, dtype=jnp.float64)
    np.testing.assert_array_equal(np.asarray(seg_sh.n_segments),
                                  np.asarray(seg.n_segments))
    np.testing.assert_allclose(np.asarray(seg_sh.seg_meta),
                               np.asarray(seg.seg_meta), rtol=1e-12)
    np.testing.assert_array_equal(np.asarray(seg_sh.mask),
                                  np.asarray(seg.mask))
    # the output really is distributed over the mesh
    shards = seg_sh.n_segments.sharding.device_set
    assert len(shards) == 8


def test_uneven_batch_rejected(packed8):
    mesh = make_mesh(n_devices=8)
    small = PackedChips(cids=packed8.cids[:3], dates=packed8.dates[:3],
                        spectra=packed8.spectra[:3], qas=packed8.qas[:3],
                        n_obs=packed8.n_obs[:3])
    with pytest.raises(ValueError, match="divide evenly"):
        detect_sharded(small, mesh)


def test_graft_entry_contract():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert int(np.asarray(out.n_segments).max()) >= 1
