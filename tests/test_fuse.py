"""Fused gram→CD→close kernel + cross-device rebalancing ring.

The fused round kernel (FIREBIRD_FUSED_FIT, pallas_ops.fused_fit_close)
must be INVISIBLE in results against the unfused Pallas-fit
configuration — same _gram_cd_core fit arithmetic, same _close_mags
magnitude program, exact-select close writes — so the golden here is
byte equality, not an envelope (the mega kernel's decision-exact
contract is the weaker cousin; this one is strict because the fused
kernel shares every float program with its baseline).  The rebalancing
ring (FIREBIRD_REBALANCE, parallel.mesh) must migrate straggler lanes
between devices of a simulated mesh without moving a single store row,
and account the migrated lanes in the occupancy/metric surface.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from firebird_tpu.ccd import kernel, params, synthetic
from firebird_tpu.ingest.packer import PackedChips

P_TEST = 32      # every detect case shares one compiled shape family

STORE_FIELDS = ("n_segments", "seg_meta", "seg_rmse", "seg_mag",
                "seg_coef", "mask", "procedure", "rounds", "round_counts",
                "vario")


@pytest.fixture(autouse=True, scope="module")
def _fuse_env():
    """The fused golden's baseline arithmetic: the Pallas fit kernel
    (the fused kernel wraps the same _gram_cd_core).  The cascade gate
    stays at its production default for the goldens — the bucketed
    re-entry doubles the traced program, and the rebalance test (which
    NEEDS the stage-2 boundary) lowers FIREBIRD_COMPACT_MIN_LANES for
    its own dispatches only, keeping this module inside the tier-1
    budget.  Module-scoped, set before the first compile; trace-time
    reads."""
    old = os.environ.get("FIREBIRD_PALLAS")
    os.environ["FIREBIRD_PALLAS"] = "fit"
    yield
    if old is None:
        os.environ.pop("FIREBIRD_PALLAS", None)
    else:
        os.environ["FIREBIRD_PALLAS"] = old


def _grid():
    return synthetic.acquisition_dates("1995-01-01", "1997-06-01", 16)


def _adversarial_pixels(seed=7):
    """Mixed + fuzz-adversarial lanes: breaks, spikes (Tmask path),
    near-empty series, all-cloud and fill lanes — scattered so the
    compaction permutation moves rows and close/fit rounds interleave."""
    rng = np.random.default_rng(seed)
    t = _grid()
    T = t.shape[0]
    px = []
    for i in range(10):
        Y = synthetic.harmonic_series(t, rng)
        if i % 2 == 0:
            Y[:, T // 2:] += 800.0            # break + re-init
        if i % 3 == 0:
            Y[:, rng.integers(0, T)] += 2500  # spike (outlier path)
        px.append((Y, np.full(T, synthetic.QA_CLEAR, np.uint16)))
    # a lane with only a handful of clear obs (init-starved)
    Ys = synthetic.harmonic_series(t, rng)
    qs = np.full(T, synthetic.QA_CLOUD, np.uint16)
    qs[:: max(T // 5, 1)] = synthetic.QA_CLEAR
    px.append((Ys, qs))
    # all-cloud and fill lanes (alt procedures, DONE from round 0)
    px.append((synthetic.harmonic_series(t, rng),
               np.full(T, synthetic.QA_CLOUD, np.uint16)))
    while len(px) < P_TEST:
        px.append((np.full((7, T), params.FILL_VALUE, np.float64),
                   np.full(T, synthetic.QA_FILL, np.uint16)))
    order = rng.permutation(P_TEST)
    return t, [px[i] for i in order]


def _pack(t, pixels, n_chips=1):
    Ys, qas = zip(*pixels)
    spectra = np.stack([np.asarray(Y, np.int16) for Y in Ys])
    spectra = spectra.transpose(1, 0, 2)[None]
    return PackedChips(
        cids=np.stack([np.full(2, i, np.int64) for i in range(n_chips)]),
        dates=np.tile(t[None], (n_chips, 1)).astype(np.int32),
        spectra=np.tile(spectra, (n_chips, 1, 1, 1)),
        qas=np.tile(np.stack(qas)[None], (n_chips, 1, 1)),
        n_obs=np.full(n_chips, t.shape[0], np.int32))


_RUNS: dict = {}


def _run(fused: bool, compact: bool):
    key = (fused, compact)
    if key not in _RUNS:
        t, px = _adversarial_pixels()
        _RUNS[key] = kernel.detect_packed(_pack(t, px), dtype=jnp.float32,
                                          compact=compact, fused=fused)
    return _RUNS[key]


def _assert_identical(on, off):
    for f in STORE_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(on, f)),
                                      np.asarray(getattr(off, f)),
                                      err_msg=f)


@pytest.mark.slow  # ~41s (two extra full kernel shapes); tier-1 keeps the compact-ON golden — the production configuration — and `make test` / fuse-smoke still run this compaction-free pure-kernel leg
def test_fused_golden_compact_off():
    """The headline contract: fused on/off byte-identical with
    compaction off (no permutation in play — pure kernel equality)."""
    _assert_identical(_run(True, False), _run(False, False))


@pytest.mark.slow  # ~65s (two more full kernel shapes); `make test` / fuse-smoke still dispatch the fused-vs-core comparison on every verify run
def test_fused_golden_compact_on():
    """Same golden under active-lane compaction: the fused kernel rides
    the dense-prefix permutation and the per-block skip guards without
    moving a bit."""
    _assert_identical(_run(True, True), _run(False, True))


def _assert_mon_golden(mon, base):
    """The whole-round fusion's contract (FIREBIRD_FUSED_FIT=mon): every
    decision field AND the coef/rmse payload byte-identical to the
    unfused chain (same _mon_scored_logic/_close_logic/_gram_cd_core
    programs), with seg_mag alone on the mega-style envelope — the
    break-magnitude median is computed from the in-VMEM PEEK run instead
    of arriving from kernel._close_mags, and a last-ulp input difference
    can flip which element the median selects (measured 1.2e-4 here)."""
    for f in STORE_FIELDS:
        if f == "seg_mag":
            continue
        np.testing.assert_array_equal(np.asarray(getattr(mon, f)),
                                      np.asarray(getattr(base, f)),
                                      err_msg=f)
    np.testing.assert_allclose(np.asarray(mon.seg_mag),
                               np.asarray(base.seg_mag),
                               rtol=5e-3, atol=1e-2)


@pytest.mark.slow  # ~40s (one extra full kernel shape on the shared baseline); `make test` / precision-smoke still dispatch the mon route every verify run
def test_fused_mon_golden_compact_off():
    """Monitor+fit+close as ONE pallas_call vs the unfused chain,
    compaction off — pure kernel equality, no permutation in play."""
    _assert_mon_golden(_run("mon", False), _run(False, False))


@pytest.mark.slow  # ~65s (one extra full kernel shape incl. the cascade); tier-1 keeps the fused_round skip-guard + knob rungs below
def test_fused_mon_golden_compact_on():
    """Same golden under active-lane compaction: the whole-round kernel
    rides the dense-prefix permutation and the per-block skip guards."""
    _assert_mon_golden(_run("mon", True), _run(False, True))


@pytest.mark.slow  # ~93s in tier-1 (the compact-ON fused run is uncached there with the goldens deselected); `make test` shares the golden's cached run and `make fuse-smoke` asserts the same occupancy-counters-moving contract every verify run
def test_fused_occupancy_still_captured():
    """The fused route must not blind the occupancy telemetry the
    roofline model feeds on."""
    seg = _run(True, True)
    r = int(np.asarray(seg.rounds)[0])
    occ = np.asarray(seg.occupancy)[0]
    assert r > 0 and (occ[:r, 0] > 0).any()
    assert int(np.asarray(seg.round_counts).reshape(-1, 3)[0, 1]) > 0


def test_fused_guard_skip_is_pass_through():
    """Skip-guard exactness for the fused kernel's active= mask: a block
    with no closing and no fitting lane must pass buffers, nseg, coefs
    and rmse through BIT-identically (the skip branch copies inputs —
    and for inactive lanes the compute branch is a no-op, so a guarded
    call equals the unguarded call everywhere)."""
    from firebird_tpu.ccd import pallas_ops

    rng = np.random.default_rng(3)
    B, T, K, S, P, BP = 7, 24, 8, 3, 16, 8
    Yt = jnp.asarray(rng.integers(100, 3000, (B, T, P)), jnp.int16)
    X = jnp.asarray(rng.standard_normal((T, K)), jnp.float32)
    t = jnp.asarray(np.sort(rng.integers(724000, 725000, T)), jnp.float32)
    # Lanes 0..BP-1 active (block 0), lanes BP.. all inactive (block 1):
    # inactive lanes carry do_fit=False and no close flags.
    act = np.zeros(P, bool)
    act[:BP] = True
    do_fit = act.copy()
    is_brk = np.zeros(P, bool)
    is_brk[1] = True
    is_tail = np.zeros(P, bool)
    is_tail[2] = True
    w_fit = (rng.integers(0, 2, (P, T)) * act[:, None]).astype(np.float32)
    bufs = tuple(jnp.asarray(rng.standard_normal((P, S * k)), jnp.float32)
                 for k in (6, B, B, B * K))
    args = (Yt, X, t, jnp.asarray(w_fit), jnp.asarray(do_fit),
            jnp.full(P, 20, jnp.int32),
            jnp.asarray(rng.integers(0, 2, (P, T)).astype(bool)),
            jnp.asarray(rng.standard_normal((P, B, K)), jnp.float32),
            jnp.ones((P, B), jnp.float32),
            jnp.asarray(rng.standard_normal((P, B)), jnp.float32),
            jnp.asarray(is_tail), jnp.asarray(is_brk),
            jnp.full(P, T // 2, jnp.int32), jnp.zeros(P, jnp.int32),
            jnp.ones(P, bool), jnp.zeros(P, jnp.int32), bufs)
    kw = dict(S=S, block_p=BP, interpret=True)
    ref = pallas_ops.fused_fit_close(*args, **kw)
    got = pallas_ops.fused_fit_close(*args, active=jnp.asarray(act), **kw)
    for r, g in zip(jax_leaves(ref), jax_leaves(got)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # and the dead block really passed its buffers through untouched
    for b_in, b_out in zip(bufs, got[0]):
        np.testing.assert_array_equal(np.asarray(b_in)[BP:],
                                      np.asarray(b_out)[BP:])


@pytest.mark.slow  # ~13s interpret trace; the mon goldens above ride the same guard path under compaction and `make precision-smoke`'s mon leg dispatches it every verify run
def test_fused_round_guard_skip_is_pass_through():
    """Skip-guard exactness for the whole-round kernel: a block with no
    monitoring and no initializing lane must pass buffers, nseg, coefs
    and rmse through BIT-identically and zero the event flags — the
    outer loop's _skip_round contract.  Inactive lanes are a compute
    no-op, so the guarded call equals the unguarded call everywhere."""
    from firebird_tpu.ccd import pallas_ops
    from firebird_tpu.ccd.sensor import LANDSAT_ARD

    rng = np.random.default_rng(9)
    B, T, K, S, P, BP = 7, 24, 8, 3, 16, 8
    Yt = jnp.asarray(rng.integers(100, 3000, (B, T, P)), jnp.int16)
    X = jnp.asarray(rng.standard_normal((T, K)), jnp.float32)
    t = jnp.asarray(np.sort(rng.integers(724000, 725000, T)), jnp.float32)
    act = np.zeros(P, bool)
    act[:BP] = True
    in_mon = act.copy()
    in_mon[0] = False
    init_ok = np.zeros(P, bool)
    init_ok[0] = True                 # lane 0: the INIT handoff path
    w_stab = np.zeros((P, T), np.int32)
    w_stab[0, ::2] = 1
    bufs = tuple(jnp.asarray(rng.standard_normal((P, S * k)), jnp.float32)
                 for k in (6, B, B, B * K))
    args = (Yt, X, t,
            jnp.ones((P, T), bool),
            jnp.asarray(rng.integers(0, 2, (P, T)).astype(bool)),
            jnp.full(P, T // 2, jnp.int32), jnp.full(P, 12, jnp.int32),
            jnp.asarray(in_mon),
            jnp.asarray(rng.standard_normal((P, B, K)), jnp.float32),
            jnp.ones((P, B), jnp.float32), jnp.ones((P, B), jnp.float32),
            jnp.asarray(init_ok), jnp.asarray(w_stab),
            jnp.full(P, 20, jnp.int32), jnp.ones(P, bool),
            jnp.zeros(P, jnp.int32), bufs)
    kw = dict(S=S, sensor=LANDSAT_ARD, change_thr=35.9, outlier_thr=31.7,
              block_p=BP, interpret=True)
    out_u = pallas_ops.fused_round(*args, **kw)
    out_g = pallas_ops.fused_round(*args, active=jnp.asarray(act), **kw)
    for r, g in zip(jax_leaves(out_u), jax_leaves(out_g)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # and the dead block really passed its buffers through untouched
    for b_in, b_out in zip(bufs, out_g[0]):
        np.testing.assert_array_equal(np.asarray(b_in)[BP:],
                                      np.asarray(b_out)[BP:])
    # event flags on the dead block are the _skip_round zeros
    ev = out_g[4]
    for f in ("is_tail", "is_brk", "is_refit", "do_fit"):
        assert not np.asarray(ev[f])[BP:].any(), f


def jax_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_rebalance_ring_row_identity_and_migration():
    """The rebalancing ring on a simulated 2-device mesh: a forced-
    ragged workload (all long-lived pixels on one device) must migrate
    lanes (lanes_migrated > 0), keep every store row identical to the
    ring-off dispatch, and land the migrated lanes in the occupancy /
    metric accounting."""
    from firebird_tpu.obs import metrics as obs_metrics
    from firebird_tpu.parallel import make_mesh
    from firebird_tpu.parallel.mesh import detect_sharded

    rng = np.random.default_rng(5)
    t = _grid()
    T = t.shape[0]
    P = 48

    def chip(n_std, brk):
        px = []
        for i in range(n_std):
            Y = synthetic.harmonic_series(t, rng)
            if brk and i % 2 == 0:
                Y[:, T // 2:] += 800.0
            px.append((Y, np.full(T, synthetic.QA_CLEAR, np.uint16)))
        while len(px) < P:
            px.append((np.full((7, T), params.FILL_VALUE, np.float64),
                       np.full(T, synthetic.QA_FILL, np.uint16)))
        return px

    busy, idle = chip(16, True), chip(2, False)
    Ys, Qs = [], []
    for px in (busy, idle):
        Y, q = zip(*px)
        Ys.append(np.stack([np.asarray(y, np.int16)
                            for y in Y]).transpose(1, 0, 2))
        Qs.append(np.stack(q))
    p = PackedChips(cids=np.stack([np.zeros(2, np.int64),
                                   np.ones(2, np.int64)]),
                    dates=np.stack([t, t]).astype(np.int32),
                    spectra=np.stack(Ys), qas=np.stack(Qs),
                    n_obs=np.array([T, T], np.int32))

    mesh = make_mesh(n_devices=2)
    old = {k: os.environ.get(k)
           for k in ("FIREBIRD_REBALANCE", "FIREBIRD_REBALANCE_THRESHOLD",
                     "FIREBIRD_COMPACT_MIN_LANES", "FIREBIRD_PALLAS")}
    try:
        # The ring lives at the stage-2 boundary: lower the cascade gate
        # so the P=48 shape builds it (trace-time read).  The ring is
        # orthogonal to WHICH kernel computes the lanes (it migrates
        # state, not programs), so this test runs the cheap lax path —
        # `make fuse-smoke` proves the same row-identity with the fused
        # kernel enabled; tracing two interpret-Pallas cascade programs
        # here would double the module's tier-1 cost for no coverage.
        os.environ["FIREBIRD_COMPACT_MIN_LANES"] = "8"
        os.environ["FIREBIRD_PALLAS"] = "0"
        os.environ["FIREBIRD_REBALANCE"] = "0"
        off = detect_sharded(p, mesh, dtype=jnp.float32, compact=True)
        os.environ["FIREBIRD_REBALANCE"] = "1"
        os.environ["FIREBIRD_REBALANCE_THRESHOLD"] = "0.1"
        on = detect_sharded(p, mesh, dtype=jnp.float32, compact=True)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    for f in ("n_segments", "seg_meta", "seg_rmse", "seg_mag", "seg_coef",
              "mask", "procedure"):
        np.testing.assert_array_equal(np.asarray(getattr(on, f)),
                                      np.asarray(getattr(off, f)),
                                      err_msg=f)
    assert off.lanes_migrated is None       # ring off -> field absent
    lm = np.asarray(on.lanes_migrated)
    assert lm.shape == (2,) and lm.sum() > 0
    # migrated-lane accounting: the occupancy capture still covers every
    # executed round, and record_occupancy lands the migration counters.
    obs_metrics.reset_registry()
    det = kernel.record_occupancy(on)
    assert det is not None and det["lanes_migrated"] == int(lm.sum())
    counters = obs_metrics.get_registry().snapshot()["counters"]
    assert counters["kernel_lanes_migrated"] == int(lm.sum())
    assert counters["rebalance_migrations"] == 1


def test_rebalance_spec_resolution(monkeypatch):
    """Knob resolution + cache-key hygiene: off / single-device meshes
    resolve to None; the spec is hashable (it rides the
    sharded_detect_fn lru_cache key) and carries the env threshold."""
    from firebird_tpu.parallel import make_mesh
    from firebird_tpu.parallel.mesh import RebalanceSpec, rebalance_spec

    mesh2 = make_mesh(n_devices=2)
    monkeypatch.delenv("FIREBIRD_REBALANCE", raising=False)
    assert rebalance_spec(mesh2) is None
    monkeypatch.setenv("FIREBIRD_REBALANCE", "1")
    monkeypatch.setenv("FIREBIRD_REBALANCE_THRESHOLD", "0.5")
    spec = rebalance_spec(mesh2)
    assert isinstance(spec, RebalanceSpec)
    assert spec.n == 2 and spec.threshold == 0.5 and spec.axis == "data"
    assert hash(spec) == hash(RebalanceSpec(axis="data", n=2,
                                            threshold=0.5, rdma=False))
    mesh1 = make_mesh(n_devices=1)
    assert rebalance_spec(mesh1) is None


def test_fused_knob_resolution(monkeypatch):
    """use_fused_fit reads the registered knob; explicit fused= wins at
    the dispatch layer regardless of env (the compact precedent)."""
    monkeypatch.delenv("FIREBIRD_FUSED_FIT", raising=False)
    assert kernel.use_fused_fit() is False
    monkeypatch.setenv("FIREBIRD_FUSED_FIT", "1")
    assert kernel.use_fused_fit() is True
    monkeypatch.setenv("FIREBIRD_FUSED_FIT", "0")
    assert kernel.use_fused_fit() is False


def test_fused_mode_tristate(monkeypatch):
    """fused_mode's tri-state: off ('', '0') -> 0, whole-round ('mon' or
    '2') -> 'mon', any other truthy value -> 1 — and use_fused_fit stays
    truthy for BOTH fused tiers (the roofline's fused modeling keys on
    it)."""
    monkeypatch.delenv("FIREBIRD_FUSED_FIT", raising=False)
    assert kernel.fused_mode() == 0
    for v, want in (("0", 0), ("1", 1), ("mon", "mon"), ("2", "mon")):
        monkeypatch.setenv("FIREBIRD_FUSED_FIT", v)
        assert kernel.fused_mode() == want, v
        assert kernel.use_fused_fit() is bool(want)


def test_mega_block_p_env_override(monkeypatch):
    """FIREBIRD_MEGA_BLOCK_P (the bench autotune's fuse_repro seed) is a
    trace-time multiple-of-128 override; 0/unset defers to the VMEM
    budget sizing."""
    from firebird_tpu.ccd import pallas_ops

    monkeypatch.delenv("FIREBIRD_MEGA_BLOCK_P", raising=False)
    assert pallas_ops._env_block_p() is None
    monkeypatch.setenv("FIREBIRD_MEGA_BLOCK_P", "256")
    assert pallas_ops._env_block_p() == 256
    monkeypatch.setenv("FIREBIRD_MEGA_BLOCK_P", "300")
    assert pallas_ops._env_block_p() == 256     # floored to the lane width
    monkeypatch.setenv("FIREBIRD_MEGA_BLOCK_P", "100")   # below one vector
    assert pallas_ops._env_block_p() is None
    monkeypatch.setenv("FIREBIRD_MEGA_BLOCK_P", "junk")
    assert pallas_ops._env_block_p() is None
