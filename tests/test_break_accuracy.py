"""Ground-truth break-date accuracy: synthetic pixels with a planted step
change must yield the exact break day (the first acquisition at/after the
change) — the proxy for BASELINE's "bit-identical break dates" north star,
and the accuracy-test class the reference lacks (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from firebird_tpu.ccd import kernel, synthetic
from firebird_tpu.ingest.packer import PackedChips
from firebird_tpu.utils import dates as dt

CHANGE = "1999-07-01"
N_PIX = 48


def _packed(seed=0):
    rng = np.random.default_rng(seed)
    t = synthetic.acquisition_dates("1995-01-01", "2003-01-01", 16)
    T = t.shape[0]
    spectra = np.zeros((1, 7, N_PIX, T), np.int16)
    changed = np.arange(N_PIX) % 2 == 0
    for p in range(N_PIX):
        Y = synthetic.harmonic_series(t, rng)
        if changed[p]:
            Y = synthetic.with_step_change(Y, t, CHANGE, delta=800.0)
        spectra[0, :, p, :] = np.clip(Y, -32768, 32767).astype(np.int16)
    qas = np.full((1, N_PIX, T), synthetic.QA_CLEAR, np.uint16)
    packed = PackedChips(
        cids=np.array([[0, 0]], np.int64),
        dates=t[None, :].astype(np.int32),
        spectra=spectra, qas=qas,
        n_obs=np.array([T], np.int32))
    return packed, t, changed


def test_break_day_is_first_exceeding_acquisition():
    packed, t, changed = _packed()
    seg = kernel.detect_packed(packed, dtype=jnp.float64)
    nseg = np.asarray(seg.n_segments)[0]
    meta = np.asarray(seg.seg_meta)[0]

    truth = int(t[np.searchsorted(t, dt.to_ordinal(CHANGE))])
    exact = 0
    for p in range(N_PIX):
        if not changed[p]:
            assert nseg[p] == 1, f"false break at unchanged pixel {p}"
            continue
        assert nseg[p] >= 2, f"missed break at changed pixel {p}"
        bday = int(round(meta[p, 0, 2]))      # first segment's break day
        assert meta[p, 0, 3] == 1.0           # confirmed (chprob 1)
        # the only tolerated inexactness: one acquisition early, the known
        # noise-driven mode (docs/DIVERGENCE.md "Known accuracy envelope")
        assert bday in (truth, int(t[np.searchsorted(t, truth) - 1])), \
            (p, bday, truth)
        exact += bday == truth
    n_changed = int(changed.sum())
    # pinned to the measured envelope (22/24 exact on this seed, the two
    # misses one acquisition early) so regressions can't hide in slack
    assert exact >= 22, (exact, n_changed)


def test_break_accuracy_across_seeds():
    """Exactness holds across several noise realizations."""
    rates = []
    for seed in (1, 2, 3):
        packed, t, changed = _packed(seed)
        seg = kernel.detect_packed(packed, dtype=jnp.float64)
        nseg = np.asarray(seg.n_segments)[0]
        meta = np.asarray(seg.seg_meta)[0]
        truth = int(t[np.searchsorted(t, dt.to_ordinal(CHANGE))])
        # every planted change must be *detected* (else exactness over the
        # detected subset could hide missed breaks entirely)
        assert all(nseg[p] >= 2 for p in range(N_PIX) if changed[p]), seed
        hits = [int(round(meta[p, 0, 2])) == truth
                for p in range(N_PIX) if changed[p]]
        rates.append(np.mean(hits))
    # measured: every changed pixel exact on all three seeds
    assert min(rates) == 1.0, rates


@pytest.mark.slow  # ~30-60s interpret-mode run; tier-1 (-m 'not slow') budget keeps the faster per-kernel parity rungs instead
def test_pallas_f32_break_agreement_with_float64(monkeypatch):
    """The full Pallas route (FIREBIRD_PALLAS=1, f32 — the production TPU
    configuration the bench autotunes toward) must reproduce float64's
    break decisions on random planted-change pixels, not just the
    equality fixtures in test_pallas."""
    packed, t, changed = _packed(6)
    monkeypatch.setenv("FIREBIRD_PALLAS", "1")
    # distinct wcap so the Pallas trace gets its own jit cache entry —
    # the flag is read at trace time and the cache is keyed on static
    # args only (same pattern as tests/test_pallas.py)
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 64)
    a = kernel.detect_packed(packed, dtype=jnp.float32)
    monkeypatch.undo()
    b = kernel.detect_packed(packed, dtype=jnp.float64)
    na, nb = (np.asarray(s.n_segments)[0] for s in (a, b))
    ma, mb = (np.asarray(s.seg_meta)[0] for s in (a, b))
    for p in range(N_PIX):
        assert na[p] == nb[p], p
        assert np.array_equal(np.round(ma[p, :na[p], 2]),
                              np.round(mb[p, :nb[p], 2])), p


def test_float32_break_agreement_with_float64():
    """The production dtype (float32) must reproduce float64's break
    decisions — BASELINE.md's secondary metric (break-date agreement) on
    the dtype actually used on device."""
    agree = total = 0
    for seed in (4, 5):
        packed, t, changed = _packed(seed)
        a = kernel.detect_packed(packed, dtype=jnp.float32)
        b = kernel.detect_packed(packed, dtype=jnp.float64)
        na = np.asarray(a.n_segments)[0]
        nb = np.asarray(b.n_segments)[0]
        ma = np.asarray(a.seg_meta)[0]
        mb = np.asarray(b.seg_meta)[0]
        for p in range(N_PIX):
            total += 1
            agree += (na[p] == nb[p]) and np.array_equal(
                np.round(ma[p, :na[p], 2]), np.round(mb[p, :nb[p], 2]))
    # measured: 100% f32/f64 agreement here and on the 720-pixel fuzz
    # sweeps (docs/ARCHITECTURE.md) — the north star is *bit-identical*
    # break dates, so no slack is tolerated
    assert agree == total, (agree, total)
