"""Streaming incremental CCDC tests: seeding from a batch result, tail
rules for absorb/exceed/break, and agreement with the batch kernel when
the same observations arrive one at a time."""

import numpy as np
import jax.numpy as jnp
import pytest

from firebird_tpu.ccd import incremental, kernel, params, synthetic
from firebird_tpu.ingest import SyntheticSource, pack
from firebird_tpu.ingest.packer import PackedChips


def slice_pixels(p: PackedChips, n: int) -> PackedChips:
    return PackedChips(cids=p.cids, dates=p.dates,
                       spectra=p.spectra[:, :, :n, :],
                       qas=p.qas[:, :n, :], n_obs=p.n_obs)


def batch_one(packed) -> kernel.ChipSegments:
    return kernel.chip_slice(kernel.detect_packed(packed, dtype=jnp.float64), 0)


@pytest.fixture(scope="module")
def seeded():
    src = SyntheticSource(seed=11, start="1995-01-01", end="2000-01-01",
                          cloud_frac=0.1, change_frac=0.0)
    full = slice_pixels(pack([src.chip(100, 200)], bucket=32), 64)
    T = int(full.n_obs[0])
    K = 6                      # stream the last K acquisitions
    cut = PackedChips(cids=full.cids, dates=full.dates,
                      spectra=full.spectra.copy(), qas=full.qas.copy(),
                      n_obs=full.n_obs - K)
    # hide the streamed tail from the batch run
    cut.qas[:, :, T - K:] = synthetic.QA_CLOUD
    return src, full, cut, T, K


def test_seed_from_batch(seeded):
    _, full, cut, T, K = seeded
    seg = batch_one(cut)
    st = incremental.StreamState.from_chip(seg)
    assert bool(np.asarray(st.active).all())
    assert np.asarray(st.nobs).min() >= params.MEOW_SIZE
    assert not np.asarray(st.needs_batch).any()


def test_stream_matches_batch_tail(seeded):
    """Streaming the last K clear acquisitions reproduces the batch end
    state for every pixel whose model was not refit in between."""
    _, full, cut, T, K = seeded
    seg_cut = batch_one(cut)
    st = incremental.StreamState.from_chip(seg_cut)
    anchor = float(full.dates[0][0])
    any_exceed = np.zeros(64, bool)
    for k in range(T - K, T):
        t_new = float(full.dates[0][k])
        x_row = incremental.design_row(t_new, anchor, np.float64)
        y_new = jnp.asarray(full.spectra[0, :, :, k].T, jnp.float64)
        qa_new = jnp.asarray(full.qas[0, :, k].astype(np.int32))
        st = incremental.step(st, jnp.asarray(x_row), y_new, qa_new, t_new)
        any_exceed |= np.asarray(st.n_exceed) > 0

    seg_full = batch_one(full)
    # Comparable pixels: same model in both batch runs (no refit between)
    # and no exceeding obs in the streamed window (an isolated exceed is
    # retroactively absorbed by the batch normal-region rules — the
    # documented streaming divergence).
    last_cut = np.maximum(np.asarray(seg_cut.n_segments) - 1, 0)
    last_full = np.maximum(np.asarray(seg_full.n_segments) - 1, 0)
    cc = np.asarray(seg_cut.seg_coef)[np.arange(64), last_cut]
    cf = np.asarray(seg_full.seg_coef)[np.arange(64), last_full]
    ok = (np.abs(cc - cf) < 1e-12).all(axis=(1, 2)) \
        & (np.asarray(seg_cut.n_segments) == np.asarray(seg_full.n_segments)) \
        & ~any_exceed
    assert ok.sum() >= 32           # the comparison is not vacuous

    meta_full = np.asarray(seg_full.seg_meta)[np.arange(64), last_full]
    np.testing.assert_allclose(np.asarray(st.end_day)[ok],
                               meta_full[ok, 1], rtol=0, atol=0)
    np.testing.assert_array_equal(
        np.asarray(st.nobs)[ok], meta_full[ok, 5].astype(int))
    np.testing.assert_array_equal(
        np.asarray(st.n_exceed)[ok],
        np.round(meta_full[ok, 3] * params.PEEK_SIZE).astype(int))


def test_break_confirmation(seeded):
    """PEEK_SIZE consecutive exceeding observations confirm a break dated
    at the first exceeding acquisition."""
    _, full, cut, T, K = seeded
    st = incremental.StreamState.from_chip(batch_one(cut))
    anchor = float(full.dates[0][0])
    days = [float(full.dates[0][T - K]) + 16 * i
            for i in range(params.PEEK_SIZE)]
    shifted = full.spectra[0, :, :, T - 1].T.astype(np.float64) + 2000.0
    for i, t_new in enumerate(days):
        x_row = incremental.design_row(t_new, anchor, np.float64)
        st = incremental.step(
            st, jnp.asarray(x_row), jnp.asarray(shifted),
            jnp.full(64, synthetic.QA_CLEAR, jnp.int32), t_new)
        if i < params.PEEK_SIZE - 1:
            assert not np.asarray(st.needs_batch).any()
    assert np.asarray(st.needs_batch).all()
    np.testing.assert_allclose(np.asarray(st.break_day), days[0])
    # further observations are ignored once a batch rerun is needed
    nobs = np.asarray(st.nobs).copy()
    st = incremental.step(
        st, jnp.asarray(incremental.design_row(days[-1] + 16, anchor,
                                               np.float64)),
        jnp.asarray(shifted),
        jnp.full(64, synthetic.QA_CLEAR, jnp.int32), days[-1] + 16)
    np.testing.assert_array_equal(np.asarray(st.nobs), nobs)


def test_stream_sentinel2_break():
    """The streaming step is sensor-generic: a 12-band S2 state absorbs
    in-model obs and confirms a break on shifted ones."""
    from firebird_tpu.ccd.sensor import SENTINEL2

    src = SyntheticSource(seed=9, start="2019-01-01", end="2021-06-01",
                          cloud_frac=0.0, change_frac=0.0, sensor=SENTINEL2)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :32, :],
                    qas=p.qas[:, :32, :], n_obs=p.n_obs, sensor=p.sensor)
    st = incremental.StreamState.from_chip(batch_one(p))
    assert np.asarray(st.active).any()
    anchor = float(p.dates[0][0])
    T = int(p.n_obs[0])
    last = p.spectra[0, :, :, T - 1].T.astype(np.float64)
    t0 = float(p.dates[0][T - 1])
    # in-model obs absorb
    nobs0 = np.asarray(st.nobs).copy()
    st = incremental.step(
        st, jnp.asarray(incremental.design_row(t0 + 10, anchor, np.float64)),
        jnp.asarray(last), jnp.full(32, synthetic.QA_CLEAR, jnp.int32),
        t0 + 10, sensor=SENTINEL2)
    act = np.asarray(st.active)
    assert (np.asarray(st.nobs)[act] == nobs0[act] + 1).all()
    # PEEK_SIZE shifted obs confirm a break on active pixels
    for i in range(params.PEEK_SIZE):
        t_new = t0 + 20 + 10 * i
        st = incremental.step(
            st, jnp.asarray(incremental.design_row(t_new, anchor,
                                                   np.float64)),
            jnp.asarray(last + 3000.0),
            jnp.full(32, synthetic.QA_CLEAR, jnp.int32), t_new,
            sensor=SENTINEL2)
    assert np.asarray(st.needs_batch)[act].all()


def test_cloudy_obs_is_noop(seeded):
    _, full, cut, T, K = seeded
    st = incremental.StreamState.from_chip(batch_one(cut))
    before = np.asarray(st.nobs).copy()
    anchor = float(full.dates[0][0])
    t_new = float(full.dates[0][T - K])
    st = incremental.step(
        st, jnp.asarray(incremental.design_row(t_new, anchor, np.float64)),
        jnp.asarray(full.spectra[0, :, :, T - K].T.astype(np.float64)),
        jnp.full(64, synthetic.QA_CLOUD, jnp.int32), t_new)
    np.testing.assert_array_equal(np.asarray(st.nobs), before)
    assert not np.asarray(st.needs_batch).any()
