"""firebird-lint (firebird_tpu.analysis) — the static contract checker.

Each rule family is proven against a hermetic fixture repo built in
tmp_path with a seeded violation, plus the engine mechanics (suppression
comments, baseline round-trip, family filtering, parse errors, CLI exit
codes) and the self-check: the REAL repo must lint clean modulo the
committed lint_baseline.json — the acceptance contract `make lint`
enforces in CI (docs/STATIC_ANALYSIS.md).
"""

import json
import textwrap

import pytest

from firebird_tpu.analysis import Baseline, run_lint
from firebird_tpu.analysis import engine


def build_repo(tmp_path, files):
    """Materialize {relpath: source} as a fixture repo rooted at tmp_path."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def rules_hit(result):
    return {f.rule for f in result.findings}


def by_rule(result, rule):
    return [f for f in result.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# jax-hotpath
# ---------------------------------------------------------------------------

def test_hotpath_host_sync_in_jitted_fn(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def f(x):
            v = x.item()
            return v
    """})
    res = run_lint(root)
    hits = by_rule(res, "hotpath-host-sync")
    assert len(hits) == 1 and hits[0].path == "mod.py"
    assert ".item()" in hits[0].message


def test_hotpath_device_get_in_while_loop_body(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import jax
        from jax import lax

        def body(carry):
            y = jax.device_get(carry)
            return carry + 1

        def run(c0):
            return lax.while_loop(lambda c: c < 3, body, c0)
    """})
    res = run_lint(root)
    assert len(by_rule(res, "hotpath-host-sync")) == 1


def test_hotpath_np_asarray_on_traced_arg(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x).sum()
    """})
    res = run_lint(root)
    hits = by_rule(res, "hotpath-host-sync")
    assert len(hits) == 1 and "np.asarray" in hits[0].message


def test_hotpath_traced_branch_vs_static_and_shape(tmp_path):
    # Branching on a traced arg is a finding; branching on a declared
    # static (resolved through a module-level tuple like _WIRE_STATICS)
    # or on .shape/.dtype is legitimate trace-time dispatch.
    root = build_repo(tmp_path, {"mod.py": """
        import jax

        _STATICS = ("mode",)

        @jax.jit
        def bad(x):
            if x > 0:
                return x
            return -x

        def good(x, mode):
            if mode == "fast":
                return x
            if x.shape[0] > 4:
                return x * 2
            return -x

        good_j = jax.jit(good, static_argnames=_STATICS)
    """})
    res = run_lint(root)
    hits = by_rule(res, "hotpath-traced-branch")
    assert len(hits) == 1
    assert "'x'" in hits[0].message or "x" in hits[0].message


def test_hotpath_statics_drift_between_jit_sites(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import jax

        def f(x, k):
            return x * k

        a = jax.jit(f, static_argnames=("k",))
        b = jax.jit(f)
    """})
    res = run_lint(root)
    assert len(by_rule(res, "hotpath-statics-drift")) == 1


def test_hotpath_aot_lower_kwargs_must_match_statics(tmp_path):
    # The PR 6 near-bug shape: a static added at the jit wrapper but not
    # to the hand-written .lower(...) AOT warm call site.
    root = build_repo(tmp_path, {"mod.py": """
        import jax

        def f(x, k, m):
            return x * k

        fj = jax.jit(f, static_argnames=("k", "m"))

        def warm(spec):
            return fj.lower(spec, k=2).compile()
    """})
    res = run_lint(root)
    hits = by_rule(res, "hotpath-statics-drift")
    assert len(hits) == 1 and "'m'" in hits[0].message


def test_hotpath_ghost_static_name(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import jax

        def f(x):
            return x

        fj = jax.jit(f, static_argnames=("nope",))
    """})
    res = run_lint(root)
    hits = by_rule(res, "hotpath-statics-drift")
    assert len(hits) == 1 and "not " in hits[0].message


def test_hotpath_untraced_code_unflagged(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import jax

        def host_side(x):
            v = x.item()
            if x > 0:
                return v
            return -v
    """})
    res = run_lint(root)
    assert "hotpath-host-sync" not in rules_hit(res)
    assert "hotpath-traced-branch" not in rules_hit(res)


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

KNOB_CONFIG = """
    KNOBS = (
        Knob(name="FIREBIRD_GOOD", field="good",
             help="a registered, documented, read knob"),
        Knob(name="FIREBIRD_DEAD", help="nothing reads this anymore"),
        Knob(name="FIREBIRD_SECRET", internal=True,
             readers=("other.py",), help="internal: no doc needed"),
        Knob(name="FIREBIRD_GHOST_FIELD", field="missing",
             help="declares a Config field that does not exist"),
    )

    class Config:
        good: str = "x"

        @classmethod
        def from_env(cls, e):
            return cls(good=e.get("FIREBIRD_GOOD", "x"))
"""

KNOB_README = """
    # fixture

    `FIREBIRD_GOOD` and `FIREBIRD_GHOST_FIELD` and `FIREBIRD_DEAD` are
    documented here; `FIREBIRD_STALE` is documented but unregistered.
"""


def test_knob_unregistered_and_reader_drift(tmp_path):
    root = build_repo(tmp_path, {
        "firebird_tpu/config.py": KNOB_CONFIG,
        "README.md": KNOB_README,
        "other.py": """
            import os

            def f():
                a = os.environ.get("FIREBIRD_UNKNOWN")     # unregistered
                b = os.environ.get("FIREBIRD_GOOD")        # reader drift
                c = os.environ.get("FIREBIRD_SECRET")      # declared reader
                return a, b, c
        """})
    res = run_lint(root)
    unreg = by_rule(res, "knob-unregistered-read")
    assert len(unreg) == 1 and "FIREBIRD_UNKNOWN" in unreg[0].message
    drift = by_rule(res, "knob-reader-drift")
    assert len(drift) == 1 and "FIREBIRD_GOOD" in drift[0].message


def test_knob_dead_undocumented_stale_and_field(tmp_path):
    root = build_repo(tmp_path, {
        "firebird_tpu/config.py": KNOB_CONFIG,
        "README.md": KNOB_README,
    })
    res = run_lint(root)
    # FIREBIRD_DEAD: registered + documented but zero reads/references.
    # (FIREBIRD_SECRET is dead too here — its declared reader file does
    # not exist in this fixture.)
    dead = {f.message.split()[0] for f in by_rule(res, "knob-dead")}
    assert dead == {"FIREBIRD_DEAD", "FIREBIRD_SECRET",
                    "FIREBIRD_GHOST_FIELD"}
    # FIREBIRD_SECRET is internal: exempt from the doc requirement (it
    # IS dead here too — its declared reader file has no reference).
    assert not any("FIREBIRD_SECRET" in f.message
                   for f in by_rule(res, "knob-undocumented"))
    # FIREBIRD_STALE: documented, never registered.
    stale = by_rule(res, "knob-doc-stale")
    assert len(stale) == 1 and stale[0].path == "README.md"
    # FIREBIRD_GHOST_FIELD: declares Config field 'missing'.
    field = by_rule(res, "knob-config-field")
    assert len(field) == 1 and "'missing'" in field[0].message


def test_env_knob_call_of_unregistered_name(tmp_path):
    # env_knob raises KeyError at RUNTIME for an unregistered name; the
    # linter must catch the drift statically (a knob renamed in KNOBS
    # with one env_knob caller missed).
    root = build_repo(tmp_path, {
        "firebird_tpu/config.py": KNOB_CONFIG,
        "firebird_tpu/mod.py": """
            from firebird_tpu.config import env_knob

            def f():
                return env_knob("FIREBIRD_NOT_REGISTERED")
        """})
    res = run_lint(root)
    hits = by_rule(res, "knob-unregistered-read")
    assert len(hits) == 1 and "FIREBIRD_NOT_REGISTERED" in hits[0].message


def test_knob_registry_required(tmp_path):
    root = build_repo(tmp_path, {
        "firebird_tpu/config.py": "X = 1\n",
    })
    res = run_lint(root)
    assert len(by_rule(res, "knob-no-registry")) == 1


# ---------------------------------------------------------------------------
# metrics-contract
# ---------------------------------------------------------------------------

METRIC_DOCS = """
    # obs

    | Metric | Kind | Meaning |
    |---|---|---|
    | `good_total` | counter | documented and registered |
    | `vanished_seconds` | histogram | documented but no code registers it |

    Prose mention: `prose_documented` gauge.
"""


def test_metric_rules(tmp_path):
    root = build_repo(tmp_path, {
        "docs/OBSERVABILITY.md": METRIC_DOCS,
        "firebird_tpu/work.py": """
            from firebird_tpu.obs.metrics import counter, gauge, histogram

            def f():
                counter("good_total", help="fine").add(1)
                counter("Bad-Name").add(1)                   # metric-name
                gauge("queue_total").set(2)                  # total-suffix
                gauge("prose_documented", help="h").set(1)
                histogram("undoc_seconds", help="h").observe(1)
        """})
    res = run_lint(root)
    name = by_rule(res, "metric-name")
    assert len(name) == 1 and "Bad-Name" in name[0].message
    suffix = by_rule(res, "metric-total-suffix")
    assert len(suffix) == 1 and "queue_total" in suffix[0].message
    # Bad-Name is rejected before further checks; queue_total is the
    # only surviving instrument registered with no help anywhere.
    helps = {f.message.split("'")[1] for f in by_rule(res, "metric-help")}
    assert helps == {"queue_total"}
    undoc = {f.message.split("'")[1]
             for f in by_rule(res, "metric-undocumented")}
    assert undoc == {"queue_total", "undoc_seconds"}
    stale = by_rule(res, "metric-doc-stale")
    assert len(stale) == 1 and "vanished_seconds" in stale[0].message


def test_metric_dynamic_name_matches_doc_wildcard(tmp_path):
    root = build_repo(tmp_path, {
        "docs/OBSERVABILITY.md": """
            | Metric | Kind | Meaning |
            |---|---|---|
            | `stream_*` | gauge | per-run streaming summary values |
        """,
        "firebird_tpu/s.py": """
            from firebird_tpu.obs.metrics import gauge

            def put(k, v):
                gauge(f"stream_{k}", help="summary value").set(v)
        """})
    res = run_lint(root)
    assert "metric-undocumented" not in rules_hit(res)
    assert "metric-doc-stale" not in rules_hit(res)


# ---------------------------------------------------------------------------
# metrics-contract: span names (call sites vs SPAN_NAMES vs docs table)
# ---------------------------------------------------------------------------

def test_span_rules_both_directions(tmp_path):
    root = build_repo(tmp_path, {
        "firebird_tpu/obs/report.py": """
            SPAN_NAMES = ("fetch", "ghost")
            DRIVER_SPAN_NAMES = ("fetch", "rogue")
        """,
        "firebird_tpu/work.py": """
            from firebird_tpu.obs import tracing

            def f():
                with tracing.span("fetch", chips=2):
                    pass
                with tracing.span("mystery"):
                    pass
        """,
        "docs/OBSERVABILITY.md": """
            | Span | Kind | Where |
            |---|---|---|
            | `fetch` | span | documented and declared |
            | `stale_span` | span | documented but undeclared |
        """})
    res = run_lint(root)
    unreg = {f.message.split("'")[1]
             for f in by_rule(res, "span-unregistered")}
    # the undeclared call site AND the DRIVER_SPAN_NAMES drift
    assert unreg == {"mystery", "rogue"}
    dead = by_rule(res, "span-dead")
    assert len(dead) == 1 and "ghost" in dead[0].message
    undoc = by_rule(res, "span-undocumented")
    assert len(undoc) == 1 and "ghost" in undoc[0].message
    stale = by_rule(res, "span-doc-stale")
    assert len(stale) == 1 and "stale_span" in stale[0].message


def test_span_rules_clean_and_skip_without_catalog(tmp_path):
    # agreement in all three places -> no findings
    root = build_repo(tmp_path, {
        "firebird_tpu/obs/report.py": 'SPAN_NAMES = ("drain",)\n',
        "firebird_tpu/w.py": """
            from firebird_tpu.obs import tracing

            def f():
                with tracing.span("drain"):
                    pass
        """,
        "docs/OBSERVABILITY.md": """
            | Span | Kind | Where |
            |---|---|---|
            | `drain` | span | fine |
        """})
    res = run_lint(root)
    assert not {r for r in rules_hit(res) if r.startswith("span-")}
    # a repo without the SPAN_NAMES catalog does not enforce spans at
    # all (fixture repos for other families keep linting hermetically)
    root2 = build_repo(tmp_path / "b", {
        "firebird_tpu/w.py": """
            from firebird_tpu.obs import tracing

            def f():
                with tracing.span("anything"):
                    pass
        """})
    res2 = run_lint(root2)
    assert not {r for r in rules_hit(res2) if r.startswith("span-")}


def test_span_match_span_method_without_name_is_ignored(tmp_path):
    # re.Match.span() and friends: no literal name argument, no finding
    root = build_repo(tmp_path, {
        "firebird_tpu/obs/report.py": 'SPAN_NAMES = ("drain",)\n',
        "docs/OBSERVABILITY.md": "| `drain` | span | fine |\n",
        "firebird_tpu/w.py": """
            import re
            from firebird_tpu.obs import tracing

            def f(m: re.Match, nm):
                a, b = m.span()
                with tracing.span(nm):       # non-literal: not checkable
                    pass
                with tracing.span("drain"):
                    pass
        """})
    res = run_lint(root)
    assert not {r for r in rules_hit(res) if r.startswith("span-")}


# ---------------------------------------------------------------------------
# thread-ownership
# ---------------------------------------------------------------------------

def test_ownership_unguarded_attr(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: _lock

            def ok(self):
                with self._lock:
                    self._q.append(1)

            def ok_locked(self):
                self._q.append(2)

            def bad(self):
                return len(self._q)
    """})
    res = run_lint(root)
    hits = by_rule(res, "ownership-unguarded-attr")
    assert len(hits) == 1 and "W.bad" in hits[0].message


def test_ownership_nested_def_resets_lock_context(tmp_path):
    # A closure handed to a thread does not inherit the enclosing
    # `with self._lock:` — access inside it must re-acquire.
    root = build_repo(tmp_path, {"mod.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: _lock

            def spawn(self):
                with self._lock:
                    def worker():
                        self._q.append(1)
                    return worker
    """})
    res = run_lint(root)
    assert len(by_rule(res, "ownership-unguarded-attr")) == 1


def test_ownership_globals(tmp_path):
    root = build_repo(tmp_path, {"mod.py": """
        import threading

        _lock = threading.Lock()
        _state = None  # guarded-by: _lock
        _latch = False

        def ok():
            global _state
            with _lock:
                _state = 1

        def bad_annotated():
            global _state
            _state = 2

        def bad_unannotated():
            global _latch
            _latch = True

        def ok_under_some_lock():
            global _latch
            with _lock:
                _latch = True
    """})
    res = run_lint(root)
    g = by_rule(res, "ownership-unguarded-global")
    assert len(g) == 1 and "bad_annotated" in g[0].message
    m = by_rule(res, "ownership-global-mutation")
    assert len(m) == 1 and "bad_unannotated" in m[0].message


def test_ownership_annotation_on_first_body_line_is_not_an_exemption(tmp_path):
    # A `# guarded-by:` on a method's FIRST statement must not turn the
    # whole method into a caller-holds-lock helper — only annotations on
    # the def/signature lines (or a *_locked name) do that.
    root = build_repo(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = 0  # guarded-by: _lock

            def bump(self):
                self._v += 1  # guarded-by: _lock

            def held(self):  # guarded-by: _lock
                self._v += 1
    """})
    res = run_lint(root)
    a = by_rule(res, "ownership-unguarded-attr")
    assert len(a) == 1 and "bump" in a[0].message


def test_ownership_annotation_on_continuation_line(tmp_path):
    # A black-wrapped assignment puts the `# guarded-by:` comment on the
    # continuation line, not stmt.lineno — it must still bind.
    root = build_repo(tmp_path, {"mod.py": """
        import threading
        import collections

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries: collections.OrderedDict = \\
                    collections.OrderedDict()  # guarded-by: _lock

            def bad(self):
                return self._entries.get(1)
    """})
    res = run_lint(root)
    a = by_rule(res, "ownership-unguarded-attr")
    assert len(a) == 1 and "_entries" in a[0].message


def test_ownership_nested_global_does_not_leak_to_outer_locals(tmp_path):
    # A nested def's `global x` must not make the OUTER function's local
    # `x` look like a global mutation, and the nested mutation must be
    # reported exactly once (attributed to the nested def).
    root = build_repo(tmp_path, {"mod.py": """
        def outer():
            x = 1

            def inner():
                global x
                x = 2
            return x
    """})
    res = run_lint(root)
    m = by_rule(res, "ownership-global-mutation")
    assert len(m) == 1
    assert "inner" in m[0].message and "outer" not in m[0].message


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, filtering, parse errors, CLI
# ---------------------------------------------------------------------------

BAD_JIT = """
    import jax

    @jax.jit
    def f(x):
        return x.item()
"""


def test_suppression_line_and_file(tmp_path):
    root = build_repo(tmp_path, {
        "a.py": """
            import jax

            @jax.jit
            def f(x):
                return x.item()  # firebird-lint: disable=hotpath-host-sync
        """,
        "b.py": """
            # firebird-lint: disable-file=hotpath-host-sync
            import jax

            @jax.jit
            def f(x):
                return x.item()

            @jax.jit
            def g(x):
                return x.tolist()
        """})
    res = run_lint(root)
    assert not res.findings
    assert res.suppressed == 3
    assert res.clean


def test_suppression_inside_string_literal_is_inert(tmp_path):
    # Prose QUOTING the suppression syntax (help text, a docstring) must
    # not disable rules — only a real comment token does.
    root = build_repo(tmp_path, {"a.py": '''
        import jax

        HELP = "silence with '# firebird-lint: disable-file=hotpath-host-sync'"

        @jax.jit
        def f(x):
            """Docs: use `# guarded-by: _lock` and
            `# firebird-lint: disable=hotpath-host-sync` as needed."""
            return x.item()
    '''})
    res = run_lint(root)
    assert len(by_rule(res, "hotpath-host-sync")) == 1
    assert res.suppressed == 0


def test_suppression_is_rule_scoped(tmp_path):
    root = build_repo(tmp_path, {"a.py": """
        import jax

        @jax.jit
        def f(x):
            if x > 0:  # firebird-lint: disable=hotpath-host-sync
                return x.item()
            return x
    """})
    res = run_lint(root)
    # The wrong rule id in the comment suppresses nothing on that line's
    # branch finding; the .item() on the NEXT line is untouched anyway.
    assert len(by_rule(res, "hotpath-traced-branch")) == 1
    assert len(by_rule(res, "hotpath-host-sync")) == 1


def test_baseline_roundtrip_absorbs_then_surfaces_regression(tmp_path):
    root = build_repo(tmp_path, {"a.py": BAD_JIT})
    first = run_lint(root)
    assert len(first.new) == 1

    bpath = str(tmp_path / "lint_baseline.json")
    Baseline().save(bpath, first.findings)
    reloaded = Baseline.load(bpath)
    assert len(reloaded) == 1

    # Same findings: absorbed, run is clean.
    again = run_lint(root, baseline=reloaded)
    assert again.clean and len(again.known) == 1 and not again.new

    # A second identical violation exceeds the baseline count: new.
    build_repo(tmp_path, {"b.py": BAD_JIT})
    worse = run_lint(root, baseline=Baseline.load(bpath))
    assert len(worse.new) == 1 and len(worse.known) == 1
    assert not worse.clean


def test_baseline_fingerprint_is_line_independent(tmp_path):
    root = build_repo(tmp_path, {"a.py": BAD_JIT})
    bpath = str(tmp_path / "b.json")
    Baseline().save(bpath, run_lint(root).findings)
    # Shift the finding down 20 lines: still absorbed.
    build_repo(tmp_path, {"a.py": "# pad\n" * 20 + textwrap.dedent(BAD_JIT)})
    res = run_lint(root, baseline=Baseline.load(bpath))
    assert res.clean


def test_baseline_rejects_unknown_schema(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"schema": "nope/9", "findings": {}}))
    with pytest.raises(ValueError):
        Baseline.load(str(p))


def test_rule_filtering_by_family_and_glob(tmp_path):
    root = build_repo(tmp_path, {
        "a.py": BAD_JIT,
        "mod.py": """
            import threading

            _lock = threading.Lock()

            def f():
                global _g
                _g = 1
        """})
    both = run_lint(root)
    assert {"hotpath-host-sync",
            "ownership-global-mutation"} <= rules_hit(both)
    fam = run_lint(root, only=["thread-ownership"])
    assert rules_hit(fam) == {"ownership-global-mutation"}
    glob = run_lint(root, only=["hotpath-*"])
    assert rules_hit(glob) == {"hotpath-host-sync"}


def test_parse_error_is_a_finding(tmp_path):
    root = build_repo(tmp_path, {"broken.py": "def f(:\n"})
    res = run_lint(root)
    assert len(res.parse_errors) == 1
    assert not res.clean


def test_tests_and_pycache_excluded(tmp_path):
    root = build_repo(tmp_path, {
        "tests/test_x.py": BAD_JIT,
        "__pycache__/junk.py": BAD_JIT,
    })
    res = run_lint(root)
    assert res.files_scanned == 0 and res.clean


def test_cli_exit_codes_update_baseline_and_json(tmp_path):
    root = build_repo(tmp_path, {"a.py": BAD_JIT})
    bpath = str(tmp_path / "lint_baseline.json")
    jpath = str(tmp_path / "out" / "lint_report.json")
    argv = ["--root", root, "--baseline", bpath]

    assert engine.main(argv + ["--json", jpath]) == 1
    doc = json.loads((tmp_path / "out" / "lint_report.json").read_text())
    assert doc["schema"] == engine.REPORT_SCHEMA
    assert doc["clean"] is False and doc["new_count"] == 1
    assert doc["per_rule"] == {"hotpath-host-sync": 1}

    assert engine.main(argv + ["--update-baseline"]) == 0
    assert engine.main(argv + ["--json", jpath]) == 0
    doc = json.loads((tmp_path / "out" / "lint_report.json").read_text())
    assert doc["clean"] is True and doc["baselined_count"] == 1

    # --no-baseline surfaces the grandfathered finding again.
    assert engine.main(argv + ["--no-baseline"]) == 1


def test_update_baseline_with_rules_filter_keeps_other_families(tmp_path):
    # --rules narrows what a run REPORTS, never what --update-baseline
    # RECORDS: refreshing one family must not drop the other families'
    # grandfathered slots from the committed file.
    root = build_repo(tmp_path, {
        "a.py": BAD_JIT,
        "mod.py": """
            def f():
                global _g
                _g = 1
        """})
    bpath = str(tmp_path / "lint_baseline.json")
    argv = ["--root", root, "--baseline", bpath]

    assert engine.main(argv + ["--rules", "hotpath-*",
                               "--update-baseline"]) == 0
    doc = json.loads((tmp_path / "lint_baseline.json").read_text())
    assert len(doc["findings"]) == 2          # both families recorded
    assert engine.main(argv) == 0             # plain run stays clean


def test_update_baseline_refreshes_json_report(tmp_path):
    # --update-baseline --json must write the POST-update state (all
    # findings absorbed), not leave a stale failing report for bench.
    root = build_repo(tmp_path, {"a.py": BAD_JIT})
    bpath = str(tmp_path / "lint_baseline.json")
    jpath = str(tmp_path / "lint_report.json")
    argv = ["--root", root, "--baseline", bpath, "--json", jpath]

    assert engine.main(argv) == 1          # stale report: clean=false
    assert engine.main(argv + ["--update-baseline"]) == 0
    doc = json.loads((tmp_path / "lint_report.json").read_text())
    assert doc["clean"] is True and doc["baselined_count"] == 1


def test_update_baseline_refuses_parse_errors(tmp_path):
    # An unparseable file ran zero rules — grandfathering that snapshot
    # would silently hide the breakage until the next plain run.
    root = build_repo(tmp_path, {"a.py": BAD_JIT, "broken.py": "def f(:\n"})
    bpath = str(tmp_path / "lint_baseline.json")
    assert engine.main(["--root", root, "--baseline", bpath,
                        "--update-baseline"]) == 1
    assert not (tmp_path / "lint_baseline.json").exists()


def test_rule_catalog_is_populated():
    engine._load_families()
    assert {"hotpath-host-sync", "knob-unregistered-read",
            "metric-doc-stale", "ownership-unguarded-attr"} \
        <= set(engine.RULE_DOCS)
    assert all(engine.RULE_DOCS.values())


# ---------------------------------------------------------------------------
# self-check: the real repo is clean modulo the committed baseline
# ---------------------------------------------------------------------------

def test_repo_lints_clean_modulo_committed_baseline():
    root = engine.default_root()
    bl = Baseline.load(engine.os.path.join(root, "lint_baseline.json"))
    res = run_lint(root, baseline=bl)
    assert res.files_scanned > 50
    assert not res.parse_errors
    assert res.clean, "new findings:\n" + "\n".join(str(f) for f in res.new)
