"""Child process for the global-mesh multihost test (test_multihost.py).

Each of two processes owns 2 virtual CPU devices; together they form one
4-device global data mesh.  The child runs parallel.mesh.detect_sharded on
its process-local chip slice and asserts the globally-sharded results are
identical to the single-device kernel on the same chips — covering the
cross-host paths VERDICT r1 flagged as untested (parallel/mesh.py):
make_array_from_process_local_data assembly, the wcap process_allgather
agreement (forced by giving the processes different acquisition cadences,
hence different local window caps), and the capacity-retry global
read_worst sync (forced by max_segments=1).
"""

import os
import sys

# Run by script path (python tests/_mp_mesh_child.py), so sys.path[0] is
# tests/, not the repo root — put the root first so firebird_tpu imports
# without requiring the package to be installed.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    pid, coord = int(sys.argv[1]), sys.argv[2]
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                               process_id=pid)
    assert jax.device_count() == 4, jax.devices()
    assert jax.local_device_count() == 2

    import numpy as np

    from firebird_tpu.ccd import kernel
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.parallel import make_mesh
    from firebird_tpu.parallel.mesh import detect_sharded, spans_processes

    # Different cadence per process -> different local window caps -> the
    # traced wcap only agrees across processes through process_allgather.
    # 2.5-year archive, not longer: the program size (and so the COLD
    # compile time, paid at every capacity rung by both processes in
    # lockstep) scales with the window cap, and on a fresh-cache host the
    # original 4-year child measured ~11 min per compile — 3 rungs blew
    # any sane timeout.  The shorter archive still closes 2 segments on
    # changed pixels (break + end), so the max_segments=1 retry sync
    # fires exactly once (1 -> 2) and every covered path stays covered.
    src = SyntheticSource(seed=3, start="1996-01-01", end="1998-07-01",
                          cadence_days=16 if pid == 0 else 8)
    cids = [(100, 200), (3100, 200), (6100, 200), (9100, 200)]
    mine = cids[pid * 2:(pid + 1) * 2]
    # bucket=128 pads BOTH processes to one T: the assembled global array
    # must have a single consistent shape across processes (the cadences
    # only differ to make the LOCAL window caps disagree — wcap depends
    # on date density, not padded length; measured here: 48 vs 24, both
    # cadences close a deepest 2 segments).
    packed = pack([src.chip(cx, cy) for cx, cy in mine], bucket=128)
    assert packed.spectra.shape[-1] == 128, packed.spectra.shape

    mesh = make_mesh()
    assert spans_processes(mesh), mesh
    seg = detect_sharded(packed, mesh, max_segments=1)   # forces retry sync

    ref = kernel.detect_packed(packed)
    for got_g, want in ((seg.n_segments, ref.n_segments),
                        (seg.seg_meta, ref.seg_meta),
                        (seg.seg_coef, ref.seg_coef)):
        shards = sorted(got_g.addressable_shards,
                        key=lambda s: s.index[0].start)
        got = np.concatenate([np.asarray(s.data) for s in shards])
        w = np.asarray(want)
        if got.ndim >= 3:                 # capacity axes may differ
            S = min(got.shape[2], w.shape[2])
            got, w = got[:, :, :S], w[:, :, :S]
        np.testing.assert_array_equal(got, w)
    # the capacity retry must actually have fired (started at 1)
    assert seg.seg_meta.shape[2] >= 2, seg.seg_meta.shape
    print(f"CHILD_OK {pid} wcap_local={kernel.window_cap(packed)} "
          f"S={seg.seg_meta.shape[2]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
