"""streamops: tile-packed checkpoint store + acquisition watcher.

Crash-safety is the point of the packed store, so the tests simulate
the crashes: torn slot writes fall back one generation, racing
same-file writers land both slots intact, and legacy ``.npz``
checkpoints migrate bit-exactly through the read-through path.  The
watcher half proves the scene -> jobs protocol: durable scene dedup
across watcher incarnations, footprint -> chip mapping, the at-most-
one-open-job-per-chip rule, and the bootstrap detect job dep'd ahead
of a checkpoint-less chip's first stream job.
"""

import json
import multiprocessing
import os
import threading
import time

import numpy as np
import pytest

from firebird_tpu import grid
from firebird_tpu.config import Config
from firebird_tpu.streamops import statestore as ss
from firebird_tpu.streamops.watcher import (LOOKBACK_SEC,
                                            AcquisitionWatcher,
                                            SceneCursor, watch_db_path)
from firebird_tpu.utils.fn import take

TILE_XY = (100.0, 200.0)


def _chips(n=3):
    return [tuple(int(v) for v in c)
            for c in take(n, grid.chips(grid.tile(x=TILE_XY[0],
                                                  y=TILE_XY[1])))]


def _mk_arrays(P=5, B=7, K=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "coefs": rng.normal(size=(P, B, K)).astype(np.float32),
        "rmse": rng.random((P, B)).astype(np.float32),
        "vario": rng.random((P, B)).astype(np.float32),
        "nobs": rng.integers(0, 100, P).astype(np.int32),
        "n_exceed": rng.integers(0, 6, P).astype(np.int32),
        "end_day": (rng.random(P) * 1000).astype(np.float32),
        "exceed_day0": np.zeros(P, np.float32),
        "break_day": np.where(rng.random(P) < 0.3,
                              728000.0, 0.0).astype(np.float32),
        "active": rng.random(P) < 0.5,
        "sday": (rng.random(P) * 1000).astype(np.float64),
        "curqa": rng.integers(0, 64, P).astype(np.int64),
        "anchor": np.float64(123.0),
        "horizon": np.float64(456.0),
    }


def _mk_state(arrays):
    import jax.numpy as jnp

    from firebird_tpu.ccd.incremental import StreamState

    st = StreamState(*(jnp.asarray(arrays[f]) for f in ss.STATE_FIELDS))
    side = {k: arrays[k] for k in ss.SIDE_FIELDS}
    return st, side


def _assert_arrays_equal(got: dict, want: dict):
    for k in ss.STATE_FIELDS + ss.SIDE_FIELDS:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]), err_msg=k)


# ---------------------------------------------------------------------------
# packed store basics
# ---------------------------------------------------------------------------

def test_roundtrip_bit_exact(tmp_path):
    store = ss.TileStateStore(str(tmp_path))
    cid = _chips(1)[0]
    arrays = _mk_arrays(seed=1)
    st, side = _mk_state(arrays)
    store.save(cid, st, side)
    st2, side2 = store.load(cid)
    got = {f: np.asarray(getattr(st2, f)) for f in ss.STATE_FIELDS}
    got.update(side2)
    _assert_arrays_equal(got, arrays)
    store.close()


def test_full_tile_o1_slots(tmp_path):
    """A full-tile file: 2500 slots addressable, first and last both
    land, the file never grows past its fixed sparse extent, and slot
    lookup is pure math (no scan)."""
    store = ss.TileStateStore(str(tmp_path))
    tile = grid.tile(x=TILE_XY[0], y=TILE_XY[1])
    cids = [tuple(int(v) for v in c) for c in grid.chips(tile)]
    assert len(cids) == 2500 == store.n_slots
    hv0, i0 = store.slot_of(cids[0])
    hvN, iN = store.slot_of(cids[-1])
    assert hv0 == hvN == (tile["h"], tile["v"])
    assert (i0, iN) == (0, 2499)
    # every chip maps to a distinct in-range slot — the O(1) address
    assert sorted(store.slot_of(c)[1] for c in cids) == list(range(2500))
    a = _mk_arrays(seed=2)
    for cid in (cids[0], cids[1234], cids[-1]):
        store.save_arrays(cid, a)
    path = store.tile_path(hv0)
    P, B, K = store._geom[hv0]
    cap, span = store._spans(P, B, K)
    assert os.path.getsize(path) == ss.FILE_HDR_SIZE + 2500 * span
    for cid in (cids[0], cids[1234], cids[-1]):
        _assert_arrays_equal(store.peek_arrays(cid), a)
    # (sparse-hole disk accounting is filesystem-dependent — overlayfs
    # materializes the extent — so only the fixed LOGICAL size asserts)
    assert store.chips() == sorted([cids[0], cids[1234], cids[-1]])
    store.close()


def test_absent_chip_raises_keyerror(tmp_path):
    store = ss.TileStateStore(str(tmp_path))
    with pytest.raises(KeyError):
        store.load(_chips(1)[0])
    store.save_arrays(_chips(2)[1], _mk_arrays())
    assert not store.exists(_chips(1)[0])
    with pytest.raises(KeyError):
        store.load(_chips(1)[0])
    store.close()


def test_lossy_state_rejected(tmp_path):
    """float64 state that does not fit float32 losslessly must refuse
    the packed layout (the npz escape hatch exists for it)."""
    store = ss.TileStateStore(str(tmp_path))
    a = _mk_arrays()
    a["coefs"] = a["coefs"].astype(np.float64) + 1e-12
    with pytest.raises(ss.StateStoreError, match="npz"):
        store.save_arrays(_chips(1)[0], a)
    store.close()


# ---------------------------------------------------------------------------
# crash safety
# ---------------------------------------------------------------------------

def _newest_bank(path, store, cid):
    """(bank_header_offset, payload_offset, length) of the live
    generation's bank — the bytes a torn write would corrupt."""
    hv, idx = store.slot_of(cid)
    geom = store._geom[hv]
    cap, span = store._spans(*geom)
    base = store._slot_offset(idx, span)
    best = None
    with open(path, "rb") as f:
        for bank in (0, 1):
            f.seek(base + bank * ss.SLOT_HDR_SIZE)
            raw = f.read(ss._SLOT_HDR.size)
            magic, gen, length, crc, cx, cy = ss._SLOT_HDR.unpack(raw)
            if magic == ss.SLOT_MAGIC and gen > 0 \
                    and (best is None or gen > best[0]):
                best = (gen, base + bank * ss.SLOT_HDR_SIZE,
                        base + 2 * ss.SLOT_HDR_SIZE + bank * cap, length)
    assert best is not None
    return best[1], best[2], best[3]


def test_torn_slot_falls_back_one_generation(tmp_path):
    store = ss.TileStateStore(str(tmp_path))
    cid = _chips(1)[0]
    gen1 = _mk_arrays(seed=10)
    gen2 = _mk_arrays(seed=11)
    store.save_arrays(cid, gen1)
    store.save_arrays(cid, gen2)
    path = store.tile_path(store.slot_of(cid)[0])
    _, payload_off, length = _newest_bank(path, store, cid)
    # tear generation 2 mid-payload (a SIGKILL between the payload
    # pwrite and... any point, really: crc catches every prefix)
    with open(path, "r+b") as f:
        f.seek(payload_off + length // 2)
        f.write(b"\xde\xad\xbe\xef" * 4)
    _assert_arrays_equal(store.peek_arrays(cid), gen1)
    assert store.tallies["torn_recoveries"] == 1
    # the next publish goes to the torn bank (gen 3 over dead gen 2)
    gen3 = _mk_arrays(seed=12)
    store.save_arrays(cid, gen3)
    _assert_arrays_equal(store.peek_arrays(cid), gen3)
    store.close()


def test_both_banks_corrupt_is_loud(tmp_path):
    store = ss.TileStateStore(str(tmp_path))
    cid = _chips(1)[0]
    store.save_arrays(cid, _mk_arrays(seed=20))
    store.save_arrays(cid, _mk_arrays(seed=21))
    hv, idx = store.slot_of(cid)
    path = store.tile_path(hv)
    cap, span = store._spans(*store._geom[hv])
    base = store._slot_offset(idx, span)
    with open(path, "r+b") as f:      # scribble over BOTH banks
        for bank in (0, 1):
            f.seek(base + 2 * ss.SLOT_HDR_SIZE + bank * cap)
            f.write(b"\xff" * cap)
    with pytest.raises(ss.StateStoreError, match="checksum"):
        store.peek_arrays(cid)
    store.close()


def _racing_writer(root, cid, seed, rounds):
    """Subprocess body: hammer one slot (jax-free on purpose — the
    statestore must be drivable without XLA in the process)."""
    store = ss.TileStateStore(root)
    for i in range(rounds):
        store.save_arrays(cid, _mk_arrays(seed=seed + i))
    store.close()


def test_two_workers_race_one_tile_file(tmp_path):
    """Two PROCESSES publishing concurrently into the same tile file —
    different slots and the SAME slot — must leave every slot loadable
    with a final generation that is one writer's complete payload."""
    cids = _chips(3)
    ctx = multiprocessing.get_context("spawn")
    procs = [
        ctx.Process(target=_racing_writer,
                    args=(str(tmp_path), cids[0], 100, 8)),
        ctx.Process(target=_racing_writer,
                    args=(str(tmp_path), cids[0], 200, 8)),
        ctx.Process(target=_racing_writer,
                    args=(str(tmp_path), cids[1], 300, 8)),
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=120)
        assert p.exitcode == 0
    store = ss.TileStateStore(str(tmp_path))
    # the contended slot holds SOME writer's final round, intact
    got = store.peek_arrays(cids[0])
    candidates = [_mk_arrays(seed=100 + 7), _mk_arrays(seed=200 + 7)]
    assert any(np.array_equal(got["coefs"], c["coefs"])
               for c in candidates)
    for c in candidates:
        if np.array_equal(got["coefs"], c["coefs"]):
            _assert_arrays_equal(got, c)
    _assert_arrays_equal(store.peek_arrays(cids[1]),
                         _mk_arrays(seed=300 + 7))
    assert store.tallies["torn_recoveries"] == 0
    store.close()


def test_same_process_thread_race(tmp_path):
    store = ss.TileStateStore(str(tmp_path))
    cid = _chips(1)[0]
    errs = []

    def hammer(seed):
        try:
            for i in range(10):
                store.save_arrays(cid, _mk_arrays(seed=seed + i))
        except Exception as e:   # noqa: BLE001 — the assert surface
            errs.append(e)

    ts = [threading.Thread(target=hammer, args=(s,)) for s in (1, 50)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    store.peek_arrays(cid)          # loadable, checksum intact
    store.close()


# ---------------------------------------------------------------------------
# legacy migration + batched load
# ---------------------------------------------------------------------------

def test_legacy_npz_migrates_bit_exact(tmp_path):
    """A per-chip .npz checkpoint (the pre-streamops layout, seeded the
    way the driver seeds it: StreamState.from_chip dtypes) reads
    through the packed store bit-exactly and lands in its slot."""
    cid = _chips(1)[0]
    arrays = _mk_arrays(seed=30)
    st, side = _mk_state(arrays)
    ss.save_state(ss.legacy_state_path(str(tmp_path), cid), st, side)

    store = ss.TileStateStore(str(tmp_path))
    assert store.exists(cid)
    st2, side2 = store.load(cid)        # read-through migration
    got = {f: np.asarray(getattr(st2, f)) for f in ss.STATE_FIELDS}
    got.update(side2)
    _assert_arrays_equal(got, arrays)
    assert store.tallies["migrations"] == 1
    # now IN the packed file: remove the npz, the slot still serves
    os.remove(ss.legacy_state_path(str(tmp_path), cid))
    _assert_arrays_equal(store.peek_arrays(cid), arrays)
    # second load comes from the slot, not another migration
    store.load(cid)
    assert store.tallies["migrations"] == 1
    store.close()


def test_load_batch_stacks_chips(tmp_path):
    store = ss.TileStateStore(str(tmp_path))
    cids = _chips(3)
    per_chip = [_mk_arrays(seed=40 + i) for i in range(3)]
    for cid, a in zip(cids, per_chip):
        store.save_arrays(cid, a)
    st, sides = store.load_batch(cids)
    assert np.asarray(st.coefs).shape == (3, 5, 7, 8)
    for i, a in enumerate(per_chip):
        for f in ss.STATE_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(st, f))[i], a[f], err_msg=f)
        for k in ss.SIDE_FIELDS:
            np.testing.assert_array_equal(sides[i][k], a[k], err_msg=k)
    store.close()


def test_open_statestore_modes(tmp_path):
    packed = Config(store_path=str(tmp_path / "s.db"),
                    stream_dir=str(tmp_path / "st"))
    assert isinstance(ss.open_statestore(packed), ss.TileStateStore)
    npz = Config(store_path=str(tmp_path / "s.db"),
                 stream_dir=str(tmp_path / "st"),
                 stream_statestore="npz")
    assert isinstance(ss.open_statestore(npz), ss.LegacyNpzStore)
    with pytest.raises(ValueError, match="STATESTORE"):
        Config(stream_statestore="tarball")


# ---------------------------------------------------------------------------
# the watcher
# ---------------------------------------------------------------------------

class ManifestSource:
    """A scripted acquisition manifest (the list_acquisitions seam)."""

    def __init__(self):
        self.scenes = []

    def land(self, scene_id, published, date, bbox=None):
        self.scenes.append({"scene_id": scene_id, "published": published,
                            "date": date, "bbox": bbox})

    def list_acquisitions(self, since=0.0):
        return [s for s in self.scenes if s["published"] > since]


@pytest.fixture()
def watch_rig(tmp_path):
    from firebird_tpu.fleet.queue import FleetQueue

    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "s.db"),
                 stream_dir=str(tmp_path / "state"),
                 source_backend="synthetic")
    src = ManifestSource()
    queue = FleetQueue(str(tmp_path / "fleet.db"))
    store = ss.TileStateStore(cfg.stream_dir)
    w = AcquisitionWatcher(cfg, *TILE_XY, number=2, source=src,
                           queue=queue, statestore=store,
                           acquired_start="1995-01-01")
    yield cfg, src, queue, store, w
    w.close()
    store.close()
    queue.close()


def test_watcher_bootstraps_then_streams(watch_rig):
    cfg, src, queue, store, w = watch_rig
    src.land("LC08_A", 1000.0, "1999-06-01")
    s = w.poll_once()
    assert s["scenes_new"] == 1 and s["scenes_enqueued"] == 1
    # no checkpoints yet: per chip one bootstrap detect + one stream
    # dep'd behind it via the queue's cross-stage machinery
    assert s["jobs"] == 4
    detect = queue.open_jobs("detect")
    stream = queue.open_jobs("stream")
    assert set(detect) == set(stream) == set(w.cids)
    for cid in w.cids:
        job = queue.job(stream[cid])
        assert job["depends_on"] == [detect[cid]]
        assert job["payload"]["published"] == 1000.0
        # half-open acquired: the scene's own date is INSIDE the range
        assert job["payload"]["acquired"] == "1995-01-01/1999-06-02"
        assert job["payload"]["cids"] == [[cid[0], cid[1]]]
        assert queue.job(detect[cid])["payload"]["bootstrap"] is True
    # a stream job is not claimable until its bootstrap acks
    lease = queue.claim("w0")
    assert lease.job_type == "detect"


def test_watcher_scene_dedup_is_durable(watch_rig):
    cfg, src, queue, store, w = watch_rig
    src.land("LC08_A", 1000.0, "1999-06-01")
    w.poll_once()
    before = queue.counts()
    # the same manifest re-listed (lookback window) enqueues nothing
    assert w.poll_once()["scenes_new"] == 0
    assert queue.counts() == before
    # a REPLACEMENT watcher (fresh process state, same durable cursor
    # db) also refuses the scene — exactly-once across incarnations
    w2 = AcquisitionWatcher(cfg, *TILE_XY, number=2, source=src,
                            queue=queue, statestore=store,
                            cursor=SceneCursor(watch_db_path(cfg)))
    try:
        assert w2.poll_once()["scenes_new"] == 0
        assert queue.counts() == before
    finally:
        w2.cursor.close()
    assert w.cursor.cursor() == 1000.0


def test_watcher_checkpointed_chip_streams_directly(watch_rig):
    cfg, src, queue, store, w = watch_rig
    for cid in w.cids:
        store.save_arrays(cid, _mk_arrays())
    src.land("LC08_B", 2000.0, "1999-07-03")
    s = w.poll_once()
    assert s["jobs"] == 2                      # stream only, no bootstrap
    assert not queue.open_jobs("detect")
    # the open stream jobs absorb the next scene (at most one open per
    # chip — the burst coalesces)
    src.land("LC08_C", 3000.0, "1999-07-19")
    s2 = w.poll_once()
    assert s2["scenes_new"] == 1 and s2["jobs"] == 0
    assert w.cursor.cursor() == 3000.0


def test_watcher_bbox_maps_to_chips(watch_rig):
    cfg, src, queue, store, w = watch_rig
    for cid in w.cids:
        store.save_arrays(cid, _mk_arrays())
    cx, cy = w.cids[1]
    src.land("LC08_D", 4000.0, "1999-08-04",
             bbox=[cx + 100, cy - 2900, cx + 200, cy - 100])
    w.poll_once()
    assert set(queue.open_jobs("stream")) == {(cx, cy)}


def test_watcher_lookback_boundary_not_skipped(watch_rig):
    """A scene published exactly AT the cursor would be invisible to a
    strict `published > cursor` manifest query; the LOOKBACK overlap
    re-lists the window and the durable dedup keeps it exactly-once."""
    cfg, src, queue, store, w = watch_rig
    for cid in w.cids:
        store.save_arrays(cid, _mk_arrays())
    src.land("LC08_T1", 5000.0, "1999-09-01")
    w.poll_once()
    # lands with the SAME publish timestamp after the cursor advanced
    src.land("LC08_T2", 5000.0, "1999-09-01")
    assert 5000.0 - LOOKBACK_SEC < w.cursor.cursor()
    s = w.poll_once()
    assert s["scenes_new"] == 1


# ---------------------------------------------------------------------------
# manifest sources + queue deps
# ---------------------------------------------------------------------------

def test_filesource_manifest_roundtrip(tmp_path):
    from firebird_tpu.ingest.sources import FileSource

    fs = FileSource(str(tmp_path))
    assert fs.list_acquisitions() == []
    fs.append_scene("S1", date="1999-06-01", published=10.0,
                    bbox=[0, 0, 3000, 3000])
    fs.append_scene("S2", date="1999-06-17", published=20.0)
    assert [s["scene_id"] for s in fs.list_acquisitions()] == ["S1", "S2"]
    assert [s["scene_id"] for s in fs.list_acquisitions(since=10.0)] \
        == ["S2"]
    # a torn trailing append is skipped, not fatal
    with open(os.path.join(str(tmp_path), fs.SCENES_FILE), "a") as f:
        f.write('{"scene_id": "S3", "pub')
    assert len(fs.list_acquisitions()) == 2


def test_synthetic_manifest_deterministic():
    from firebird_tpu.ingest.sources import SyntheticSource

    src = SyntheticSource(seed=3, start="1999-01-01", end="1999-03-01",
                          cadence_days=16)
    a = src.list_acquisitions()
    assert a == src.list_acquisitions()
    assert [s["date"] for s in a][:2] == ["1999-01-01", "1999-01-17"]
    assert all(s["published"] > 0 for s in a)
    assert src.list_acquisitions(since=a[0]["published"])[0]["scene_id"] \
        == a[1]["scene_id"]


def test_enqueue_unique_chip_depends_on(tmp_path):
    from firebird_tpu.fleet.queue import FleetQueue

    q = FleetQueue(str(tmp_path / "fleet.db"))
    try:
        boot = q.enqueue_unique_chip("detect", {"cx": 1, "cy": 2,
                                                "bootstrap": True})
        sj = q.enqueue_unique_chip("stream", {"cx": 1, "cy": 2},
                                   depends_on=[boot])
        assert q.job(sj)["depends_on"] == [boot]
        lease = q.claim("w")
        assert lease.job_id == boot
        q.ack(lease)
        lease2 = q.claim("w")
        assert lease2 is not None and lease2.job_id == sj
        with pytest.raises(ValueError, match="unknown job ids"):
            q.enqueue_unique_chip("stream", {"cx": 9, "cy": 9},
                                  depends_on=[999])
    finally:
        q.close()


def test_alert_freshness_slo_prefers_end_to_end():
    from firebird_tpu.obs import slo

    h = lambda p95: {"count": 4, "p95": p95}
    both = {"histograms": {"acquisition_to_alert_seconds": h(12.0),
                           "alert_visible_seconds": h(1.0)}}
    out = slo.evaluate_snapshot(both, spec="alert_freshness=60")
    (obj,) = out["objectives"]
    assert obj["metric"] == "acquisition_to_alert_seconds"
    assert obj["value_sec"] == 12.0 and obj["ok"] is True
    only_local = {"histograms": {"alert_visible_seconds": h(1.0)}}
    (obj2,) = slo.evaluate_snapshot(
        only_local, spec="alert_freshness=60")["objectives"]
    assert obj2["metric"] == "alert_visible_seconds"
    assert obj2["value_sec"] == 1.0
    (obj3,) = slo.evaluate_snapshot(
        {"histograms": {}}, spec="alert_freshness=60")["objectives"]
    assert obj3["ok"] is None and obj3["value_sec"] is None


def test_watcher_revives_dead_bootstrap(watch_rig):
    """A bootstrap that dead-letters must not strand its chip: the
    dep'd stream job stays pending-blocked (absorbing every future
    enqueue), so the next scene's poll requeues the dead bootstrap
    with a fresh budget and the chain drains."""
    cfg, src, queue, store, w = watch_rig
    src.land("LC08_A", 1000.0, "1999-06-01")
    w.poll_once()
    detect = queue.open_jobs("detect")
    # the bootstraps crash-loop to death (attempt budgets spent)
    for _ in range(cfg.fleet_max_attempts * len(w.cids)):
        lease = queue.claim("w0")
        assert lease.job_type == "detect"
        queue.fail(lease, RuntimeError("source outage"))
    assert queue.counts()["dead"] == len(w.cids)
    assert queue.claim("w0") is None     # stream jobs blocked, wedged
    # next scene: the watcher revives the dead bootstraps
    src.land("LC08_B", 2000.0, "1999-06-17")
    w.poll_once()
    assert queue.counts()["dead"] == 0
    lease = queue.claim("w0")
    assert lease is not None and lease.job_type == "detect"
    # bootstrap acks (checkpoint seeded) -> the stream job unblocks
    store.save_arrays((lease.payload["cx"], lease.payload["cy"]),
                      _mk_arrays())
    queue.ack(lease)
    nxt = {queue.claim("w0").job_type, queue.claim("w0").job_type}
    assert "stream" in nxt
    assert set(queue.open_jobs("detect")) <= set(detect)


def test_void_unrecoverable_slot(tmp_path):
    """Both banks corrupt -> void() clears the slot so exists() turns
    False and the next stream run can re-bootstrap (the self-healing
    path behind driver/stream.update_one's StateStoreError catch)."""
    store = ss.TileStateStore(str(tmp_path))
    cid = _chips(1)[0]
    store.save_arrays(cid, _mk_arrays(seed=60))
    hv, idx = store.slot_of(cid)
    cap, span = store._spans(*store._geom[hv])
    base = store._slot_offset(idx, span)
    with open(store.tile_path(hv), "r+b") as f:
        for bank in (0, 1):
            f.seek(base + 2 * ss.SLOT_HDR_SIZE + bank * cap)
            f.write(b"\xff" * cap)
    assert store.exists(cid)             # headers still parse
    with pytest.raises(ss.StateStoreError):
        store.load(cid)
    store.void(cid)
    assert not store.exists(cid)
    with pytest.raises(KeyError):
        store.load(cid)
    # the slot is reusable after the void
    store.save_arrays(cid, _mk_arrays(seed=61))
    _assert_arrays_equal(store.peek_arrays(cid), _mk_arrays(seed=61))
    store.close()


def test_float64_config_routes_to_npz_store(tmp_path):
    """FIREBIRD_DTYPE=float64 state cannot fit the packed f32 layout
    losslessly — the store factory must route it to the npz layout
    instead of crashing the first checkpoint save."""
    cfg = Config(store_path=str(tmp_path / "s.db"),
                 stream_dir=str(tmp_path / "st"), dtype="float64")
    assert isinstance(ss.open_statestore(cfg), ss.LegacyNpzStore)


def test_default_acquired_covers_today():
    """Half-open windows: the default range must END tomorrow so an
    observation acquired today — the freshest one — is inside it."""
    import datetime

    from firebird_tpu.utils import dates as dt

    lo, hi = dt.acquired_range(dt.default_acquired())
    assert hi == datetime.date.today().toordinal() + 1


def test_watcher_requires_manifest_source(tmp_path):
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "s.db"),
                 stream_dir=str(tmp_path / "state"))

    class NoManifest:
        pass

    from firebird_tpu.fleet.queue import FleetQueue

    q = FleetQueue(str(tmp_path / "fleet.db"))
    try:
        with pytest.raises(ValueError, match="list_acquisitions"):
            AcquisitionWatcher(cfg, *TILE_XY, number=1,
                               source=NoManifest(), queue=q)
    finally:
        q.close()
