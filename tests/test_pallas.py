"""Pallas CD-loop kernel: bit-level parity with the lax path (interpret
mode on CPU; the same kernel runs compiled on TPU under FIREBIRD_PALLAS=1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firebird_tpu.ccd import harmonic, kernel, params, pallas_ops


@pytest.fixture(autouse=True)
def _clear_pallas_env(monkeypatch):
    """Every parity test's reference run must trace the default XLA path:
    an ambient FIREBIRD_PALLAS (e.g. from a bench shell) would route both
    sides through the same kernels and make the comparison vacuous."""
    monkeypatch.delenv("FIREBIRD_PALLAS", raising=False)


def _systems(P=37, B=7, T=60, dtype=jnp.float32, seed=0):
    """Realistic (G, c, diag, mask) built exactly as _fit_lasso_coefs does."""
    rng = np.random.default_rng(seed)
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], params.MAX_COEFS), dtype)
    Y = jnp.asarray(rng.normal(1000, 300, (P, B, T)), dtype)
    w = jnp.asarray((rng.random((P, T)) < 0.8), dtype)
    K = params.MAX_COEFS
    n = jnp.maximum(jnp.sum(w, -1), 1.0)
    XX = (X[:, :, None] * X[:, None, :]).reshape(-1, K * K)
    G = (w @ XX).reshape(-1, K, K) / n[:, None, None]
    c = jnp.einsum("pbt,tc->pbc", Y * w[:, None, :], X) / n[:, None, None]
    diag = jnp.maximum(jnp.diagonal(G, axis1=-2, axis2=-1), 1e-12)
    nc = rng.choice([4, 6, 8], P)
    mask = jnp.asarray(np.arange(K)[None, :] < nc[:, None])
    return G, c, diag, mask


# The two CD implementations reduce over k in different association
# orders, so they differ at machine epsilon per update; 50 iterations of
# soft-thresholding amplify that slightly in f32.  Tolerances mirror the
# kernel-vs-oracle parity ladder (test_ccd_reference).
_TOL = {jnp.dtype(jnp.float32): dict(rtol=1e-2, atol=1e-2),
        jnp.dtype(jnp.float64): dict(rtol=1e-8, atol=1e-8)}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_pallas_cd_matches_lax(dtype):
    G, c, diag, mask = _systems(dtype=dtype)
    ref = kernel._lasso_cd_lax(G, c, diag, mask)
    got = pallas_ops.lasso_cd(G, c, diag, mask, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               **_TOL[jnp.dtype(dtype)])


def test_pallas_cd_under_vmap():
    """The detect path calls the CD loop under vmap over chips."""
    Gs, cs, ds, ms = zip(*[_systems(P=16, dtype=jnp.float64, seed=s)
                           for s in range(3)])
    G, c, d, m = (jnp.stack(x) for x in (Gs, cs, ds, ms))
    ref = jax.vmap(kernel._lasso_cd_lax)(G, c, d, m)
    got = jax.vmap(lambda *a: pallas_ops.lasso_cd(*a, interpret=True))(
        G, c, d, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               **_TOL[jnp.dtype(jnp.float64)])


@pytest.mark.slow  # ~30-60s interpret-mode run; tier-1 (-m 'not slow') budget keeps the faster per-kernel parity rungs instead
def test_pallas_flag_routes_full_detect(monkeypatch):
    """FIREBIRD_PALLAS=1 routes the whole chip detector through the Pallas
    CD loop with results matching the default path."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    src = SyntheticSource(seed=21, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :48, :], qas=p.qas[:, :48, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float64)
    monkeypatch.setenv("FIREBIRD_PALLAS", "1")
    # distinct wcap avoids reusing the compiled default-path program
    got = kernel._detect_batch_wire(
        *(jnp.asarray(a) for a in _wire_args(p)),
        dtype=jnp.dtype(jnp.float64), wcap=kernel.window_cap(p) + 8,
        sensor=p.sensor)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_allclose(np.asarray(got.seg_meta),
                               np.asarray(ref.seg_meta), atol=1e-9)


def _wire_args(p):
    # The all-integer wire tuple (designs build on device).
    return kernel.wire_args(p)


@pytest.mark.slow  # ~27s interpret-mode run; tier-1 (-m 'not slow') keeps the lax sharded parity (test_parallel) + single-device Pallas rungs
def test_pallas_inside_sharded_detect(monkeypatch):
    """The sharded production path (shard_map over the mesh) composes with
    the Pallas CD loop: each shard runs its own single-device Mosaic call,
    so no SPMD partitioning rule is needed."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips
    from firebird_tpu.parallel import make_mesh
    from firebird_tpu.parallel.mesh import detect_sharded

    src = SyntheticSource(seed=21, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    p = pack([src.chip(100 + 3000 * i, 200) for i in range(2)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :48, :], qas=p.qas[:, :48, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    mesh = make_mesh(n_devices=2)
    ref = detect_sharded(p, mesh, dtype=jnp.float64)
    monkeypatch.setenv("FIREBIRD_PALLAS", "1")
    # fresh trace: a bigger wcap changes the static args, busting the cache
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 8)
    got = detect_sharded(p, mesh, dtype=jnp.float64)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_allclose(np.asarray(got.seg_meta),
                               np.asarray(ref.seg_meta), atol=1e-9)


def test_monitor_chain_matches_jnp_reference():
    """pallas_ops.monitor_chain (interpret mode) reproduces
    kernel._monitor_chain exactly on randomized round states — every
    output, including the argmax no-hit defaults and INF sentinels."""
    from firebird_tpu.ccd import pallas_ops

    rng = np.random.default_rng(5)
    P, T = 137, 96           # odd sizes force the block padding path
    for trial in range(4):
        alive = rng.random((P, T)) < 0.8
        s = jnp.asarray(
            rng.gamma(2.0, 6.0, (P, T)).astype(np.float32))
        included = jnp.asarray((rng.random((P, T)) < 0.4) & alive)
        rank = jnp.cumsum(jnp.asarray(alive), -1) - 1
        cur_k = jnp.asarray(rng.integers(0, T, P), jnp.int32)
        n_last_fit = jnp.asarray(rng.integers(1, 40, P), jnp.int32)
        in_mon = jnp.asarray(rng.random(P) < 0.7)
        alive = jnp.asarray(alive)
        kw = dict(change_thr=11.07, outlier_thr=15.09)
        want = kernel._monitor_chain(s, alive, included, rank, cur_k,
                                     n_last_fit, in_mon, **kw)
        got = pallas_ops.monitor_chain(s, alive, included, rank, cur_k,
                                       n_last_fit, in_mon, interpret=True,
                                       **kw)
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(want[k]), err_msg=k)


@pytest.mark.slow  # ~30-60s interpret-mode run; tier-1 (-m 'not slow') budget keeps the faster per-kernel parity rungs instead
def test_monitor_chain_in_detect_matches_default(monkeypatch):
    """FIREBIRD_PALLAS=1 routes the monitor chain (and the CD loop)
    through Pallas; full-detect results must equal the default path."""
    from firebird_tpu.ingest import SyntheticSource, pack

    src = SyntheticSource(seed=33, start="1995-01-01", end="1999-01-01",
                          cloud_frac=0.15)
    p = pack([src.chip(100, 200)], bucket=32)
    from firebird_tpu.ingest.packer import PackedChips
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :64, :], qas=p.qas[:, :64, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "1")
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 16)
    got = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_allclose(np.asarray(got.seg_meta),
                               np.asarray(ref.seg_meta), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got.mask), np.asarray(ref.mask))


def test_monitor_scored_matches_jnp_reference():
    """pallas_ops.monitor_chain_scored (interpret) reproduces the XLA
    score + kernel._monitor_chain pipeline on randomized round states,
    reading wire-dtype int16 detection-band spectra."""
    from firebird_tpu.ccd import harmonic, pallas_ops

    rng = np.random.default_rng(11)
    P, T, nb, K = 137, 96, 5, params.MAX_COEFS
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], K), jnp.float32)
    for trial in range(3):
        Yd = rng.integers(0, 8000, (nb, T, P)).astype(np.int16)
        coefs = jnp.asarray(rng.normal(0, 1, (P, nb, K)) * 100, jnp.float32)
        dden = jnp.asarray(np.abs(rng.normal(150, 40, (P, nb))) + 1,
                           jnp.float32)
        alive = rng.random((P, T)) < 0.8
        included = jnp.asarray((rng.random((P, T)) < 0.4) & alive)
        rank = jnp.cumsum(jnp.asarray(alive), -1) - 1
        cur_k = jnp.asarray(rng.integers(0, T, P), jnp.int32)
        n_last_fit = jnp.asarray(rng.integers(1, 40, P), jnp.int32)
        in_mon = jnp.asarray(rng.random(P) < 0.7)
        alive = jnp.asarray(alive)
        kw = dict(change_thr=11.07, outlier_thr=15.09)

        # the XLA path: [P,nb,T] prediction einsum -> score -> chain
        Yp = jnp.asarray(Yd.transpose(2, 0, 1), jnp.float32)   # [P,nb,T]
        pred = jnp.einsum("pbc,tc->pbt", coefs, X)
        s = jnp.sum(((Yp - pred) / dden[:, :, None]) ** 2, axis=1)
        want = kernel._monitor_chain(s, alive, included, rank, cur_k,
                                     n_last_fit, in_mon, **kw)
        got = pallas_ops.monitor_chain_scored(
            jnp.asarray(Yd), coefs, dden, X, alive, included, cur_k,
            n_last_fit, in_mon, interpret=True, **kw)
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(want[k]), err_msg=k)


def test_score_kernel_in_detect_matches_default(monkeypatch):
    """FIREBIRD_PALLAS=score routes the monitor score+chain through the
    score-fused kernel; segment decisions must equal the default path."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    src = SyntheticSource(seed=66, start="1995-01-01", end="1999-01-01",
                          cloud_frac=0.15)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :64, :], qas=p.qas[:, :64, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "score")
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 40)
    got = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_array_equal(np.asarray(got.seg_meta[..., :3]),
                                  np.asarray(ref.seg_meta[..., :3]))
    np.testing.assert_array_equal(np.asarray(got.mask), np.asarray(ref.mask))


def test_tmask_bad_matches_jnp_reference():
    """pallas_ops.tmask_bad (interpret) reproduces kernel._tmask_bad on
    randomized windows — including degenerate all-masked and constant
    (non-PD Gram -> NaN -> flag-nothing) pixels."""
    from firebird_tpu.ccd import pallas_ops

    rng = np.random.default_rng(9)
    P, W, nt = 153, 24, 5
    Xtw = rng.normal(0, 1, (P, W, nt)).astype(np.float32)
    Xtw[:, :, 0] = 1.0
    Y2 = (400 + 80 * rng.normal(0, 1, (P, 2, W))).astype(np.float32)
    # a few outliers the screen should flag
    Y2[rng.random((P, 2, W)) < 0.05] += 900
    nwin = rng.integers(0, W + 1, P)
    w = (np.arange(W)[None, :] < nwin[:, None]).astype(np.float32)
    vario2 = np.abs(rng.normal(40, 10, (P, 2))).astype(np.float32)
    Y2[7] = 444.0                      # constant series -> singular Gram
    want = np.asarray(kernel._tmask_bad(
        jnp.asarray(Xtw), jnp.asarray(Y2), jnp.asarray(w),
        jnp.asarray(vario2)))
    got = np.asarray(pallas_ops.tmask_bad(
        jnp.asarray(Xtw), jnp.asarray(Y2), jnp.asarray(w),
        jnp.asarray(vario2), interpret=True))
    assert want.any() and not want.all()
    np.testing.assert_array_equal(got, want)


def test_full_pallas_detect_matches_default(monkeypatch):
    """FIREBIRD_PALLAS=lasso,monitor,tmask routes all three components
    through Pallas; full-detect results must equal the default path."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    src = SyntheticSource(seed=44, start="1995-01-01", end="1999-01-01",
                          cloud_frac=0.2)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :64, :], qas=p.qas[:, :64, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "lasso,monitor,tmask")
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 24)
    got = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_allclose(np.asarray(got.seg_meta),
                               np.asarray(ref.seg_meta), atol=1e-5)


@pytest.mark.slow  # ~61s W-unrolled interpret run; tier-1 (-m 'not slow') keeps test_full_pallas_detect_matches_default (the init block runs inside it) and `make test` / fuse-smoke still run this rung
def test_init_window_matches_init_block():
    """pallas_ops.init_window (interpret) reproduces kernel._init_block
    on randomized mid-loop round states, reading wire int16 spectra."""
    import functools
    from firebird_tpu.ccd import harmonic, pallas_ops
    from firebird_tpu.ccd.sensor import LANDSAT_ARD

    rng = np.random.default_rng(17)
    P, B, T, W = 137, 7, 96, 24
    t = np.float64(np.sort(rng.integers(729000, 730500, T)))
    X = jnp.asarray(harmonic.design_matrix(t, t[0], params.MAX_COEFS),
                    jnp.float32)
    Xt_full = harmonic.design_matrix(t, t[0], params.TMASK_COEFS + 1)
    Xt = jnp.asarray(np.concatenate([Xt_full[:, :1], Xt_full[:, 2:]], 1),
                     jnp.float32)
    Yi = rng.integers(0, 8000, (B, P, T)).astype(np.int16)
    Y = jnp.asarray(Yi.transpose(1, 0, 2), jnp.float32)       # [P,B,T]
    Yt = jnp.asarray(Yi.transpose(0, 2, 1))                   # [B,T,P] i16
    vario = jnp.asarray(np.abs(rng.normal(100, 30, (P, B))) + 1,
                        jnp.float32)
    alive = jnp.asarray(rng.random((P, T)) < 0.7)
    cur_i = jnp.asarray(rng.integers(0, T // 2, P), jnp.int32)
    phase = jnp.asarray(
        rng.choice([kernel.PHASE_INIT, kernel.PHASE_MONITOR,
                    kernel.PHASE_DONE], P, p=[0.6, 0.2, 0.2]), jnp.int32)

    res = dict(X=X, Xt=Xt, t=jnp.asarray(t, jnp.float32), Y=Y, Yt=Yt,
               XX=(X[:, :, None] * X[:, None, :]).reshape(T, -1),
               vario=vario)
    st = dict(alive=alive, cur_i=cur_i, phase=phase)
    fit = functools.partial(kernel._fit_chip, fit_pallas=False,
                            on_tpu=False)
    want = kernel._init_block(res, st, sensor=LANDSAT_ARD, W=W,
                              fdtype=jnp.float32, fit=fit, f32_ok=True)
    got = pallas_ops.init_window(alive, cur_i, phase == kernel.PHASE_INIT,
                                 res["t"], X, Xt, Yt, vario, W=W,
                                 sensor=LANDSAT_ARD, interpret=True)
    assert set(got) == set(want)
    # integer/boolean outputs must agree exactly; the stability verdict
    # (init_ok/init_bad) depends on an f32 fit whose Gram accumulation
    # order differs between the XLA dot and the kernel core, so allow a
    # tiny borderline disagreement there (none observed on this seed).
    exact = ["init_nowin", "init_tm", "has_adv", "i_next_tm", "i_adv",
             "j", "alive_init", "w_stab", "n_ok"]
    for k in exact:
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k]), err_msg=k)
    for k in ["init_ok", "init_bad"]:
        diff = np.mean(np.asarray(got[k]) != np.asarray(want[k]))
        assert diff <= 0.02, (k, diff)


@pytest.mark.slow  # ~30-60s interpret-mode run; tier-1 (-m 'not slow') budget keeps the faster per-kernel parity rungs instead
def test_init_kernel_in_detect_matches_default(monkeypatch):
    """FIREBIRD_PALLAS=init routes the whole INIT block through the fused
    window kernel; segment decisions must equal the default path."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    src = SyntheticSource(seed=77, start="1995-01-01", end="1999-01-01",
                          cloud_frac=0.15)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :64, :], qas=p.qas[:, :64, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "init")
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 48)
    got = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_array_equal(np.asarray(got.seg_meta[..., :3]),
                                  np.asarray(ref.seg_meta[..., :3]))
    np.testing.assert_array_equal(np.asarray(got.mask), np.asarray(ref.mask))


@pytest.mark.slow  # ~30-60s interpret-mode run; tier-1 (-m 'not slow') budget keeps the faster per-kernel parity rungs instead
def test_full_pallas_sentinel2_matches_default(monkeypatch):
    """All Pallas components under the 12-band Sentinel-2 sensor layout:
    the bench's S2 rung runs with the autotuned FIREBIRD_PALLAS set, so
    every kernel must be sensor-generic (band counts, detection/Tmask
    subsets, no thermal)."""
    from firebird_tpu.ccd.sensor import SENTINEL2
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    src = SyntheticSource(seed=88, start="2019-01-01", end="2021-01-01",
                          cloud_frac=0.15, sensor=SENTINEL2)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :64, :], qas=p.qas[:, :64, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "1")
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 56)
    got = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_array_equal(np.asarray(got.seg_meta[..., :3]),
                                  np.asarray(ref.seg_meta[..., :3]))
    np.testing.assert_array_equal(np.asarray(got.mask), np.asarray(ref.mask))


def test_use_pallas_component_parsing(monkeypatch):
    for env, lasso, monitor, tmask in [
            ("0", False, False, False), ("", False, False, False),
            ("1", True, True, True),
            ("lasso", True, False, False),
            ("monitor,tmask", False, True, True),
            (" lasso , tmask ", True, False, True)]:
        monkeypatch.setenv("FIREBIRD_PALLAS", env)
        assert kernel.use_pallas("lasso") is lasso, env
        assert kernel.use_pallas("monitor") is monitor, env
        assert kernel.use_pallas("tmask") is tmask, env


def test_pallas_fit_matches_fit_lasso():
    """pallas_ops.lasso_fit (interpret) matches kernel._fit_lasso on the
    same systems, reading wire-dtype int16 spectra (widened in-register,
    exact)."""
    from firebird_tpu.ccd import harmonic, pallas_ops

    rng = np.random.default_rng(3)
    P, B, T = 141, 7, 60
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], params.MAX_COEFS),
                    jnp.float32)
    Yi = rng.integers(0, 8000, (P, B, T)).astype(np.int16)
    w = jnp.asarray((rng.random((P, T)) < 0.8), jnp.float32)
    nc = rng.choice([4, 6, 8], P)
    mask = jnp.asarray(np.arange(8)[None, :] < nc[:, None])
    ref_b, ref_r = kernel._fit_lasso(X, jnp.asarray(Yi, jnp.float32), w,
                                     mask)
    Yt = jnp.asarray(Yi.transpose(1, 2, 0))           # [B,T,P] int16
    got_b, got_r = pallas_ops.lasso_fit(Yt, w, X, mask, with_rmse=True,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got_b), np.asarray(ref_b),
                               rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(got_r), np.asarray(ref_r),
                               rtol=1e-2, atol=1e-2)
    nb, nr = pallas_ops.lasso_fit(Yt, w, X, mask, with_rmse=False,
                                  interpret=True)
    np.testing.assert_array_equal(np.asarray(nb), np.asarray(got_b))
    assert not np.asarray(nr).any()


@pytest.mark.slow  # ~65s interpret-mode run; tier-1 (-m 'not slow') keeps test_pallas_fit_matches_fit_lasso + the guarded-fit rungs and `make test` / fuse-smoke still run the fit-in-detect route
def test_fit_kernel_in_detect_matches_default(monkeypatch):
    """FIREBIRD_PALLAS=fit routes all three batched Lasso fits through the
    fused Pallas kernel; segment decisions must equal the default path."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips

    src = SyntheticSource(seed=55, start="1995-01-01", end="1999-01-01",
                          cloud_frac=0.15)
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :64, :], qas=p.qas[:, :64, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    ref = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "fit")
    monkeypatch.setattr(kernel, "window_cap",
                        lambda pk, _orig=kernel.window_cap: _orig(pk) + 32)
    got = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_array_equal(np.asarray(got.seg_meta[..., :3]),
                                  np.asarray(ref.seg_meta[..., :3]))
    np.testing.assert_array_equal(np.asarray(got.mask), np.asarray(ref.mask))


@pytest.mark.slow  # ~67s, the suite's single heaviest test; tier-1 keeps the per-kernel mega rungs (init/fit/tmask parity) and `make test` / fuse-smoke still run the full mega-vs-core equality
def test_detect_mega_matches_batch_core(monkeypatch):
    """FIREBIRD_PALLAS=mega routes the ENTIRE event loop through the
    whole-loop kernel (one pallas_call, VMEM-resident spectra, per-block
    while_loop) and reproduces the default XLA loop's decisions on a
    break/spike/QA-mixed workload spanning multiple pixel blocks."""
    from firebird_tpu.ccd import synthetic
    from firebird_tpu.ccd.sensor import LANDSAT_ARD
    from firebird_tpu.ccd import pallas_ops

    # Force 2 pixel blocks so block-boundary/padding paths execute
    # (production BP would be >= the whole test chip).
    monkeypatch.setattr(pallas_ops, "mega_block_p",
                        lambda *a, **k: 128)

    rng = np.random.default_rng(31)
    C, B, P, T = 2, 7, 200, 72
    t = np.stack([np.sort(rng.integers(724000, 724000 + 9000, T)).astype(
        np.float64) for _ in range(C)])
    X = np.stack([harmonic.design_matrix(t[c], t[c, 0], params.MAX_COEFS)
                  for c in range(C)])
    Xt_full = np.stack([harmonic.design_matrix(t[c], t[c, 0],
                                               params.TMASK_COEFS + 1)
                        for c in range(C)])
    Xt = np.concatenate([Xt_full[:, :, :1], Xt_full[:, :, 2:]], -1)
    valid = np.ones((C, T), bool)
    Y = (rng.integers(400, 3000, (C, 1, P, 1))
         + rng.normal(0, 50, (C, B, P, T)))
    # step changes on half the pixels (break + re-init path), spikes on
    # a few (Tmask/outlier path)
    for c in range(C):
        for p_ in range(0, P, 2):
            cpos = rng.integers(T // 3, 2 * T // 3)
            Y[c, :, p_, cpos:] += rng.choice([-1.0, 1.0]) * rng.uniform(
                400, 1200)
        for p_ in range(0, P, 7):
            s = rng.integers(0, T - 1)
            Y[c, :, p_, s] += 2500
    Y = Y.astype(np.int16)
    qa = np.full((C, P, T), 1 << params.QA_CLEAR_BIT, np.int32)
    # some cloudy/fill lanes -> alt procedures + padded-lane inertness
    qa[:, P - 8:, ::2] = 1 << params.QA_CLOUD_BIT
    qa[:, P - 3:, :] = 1 << params.QA_FILL_BIT

    args = (jnp.asarray(X, jnp.float32), jnp.asarray(Xt, jnp.float32),
            jnp.asarray(t, jnp.float32), jnp.asarray(valid),
            jnp.asarray(Y), jnp.asarray(qa))

    ref = kernel._detect_batch_core(*args, wcap=24, dtype=jnp.float32)
    rn = np.asarray(ref.n_segments)

    monkeypatch.setenv("FIREBIRD_PALLAS", "mega")
    jax.clear_caches()
    try:
        got = kernel._detect_batch_core(*args, wcap=24, dtype=jnp.float32)
        gn = np.asarray(got.n_segments)
    finally:
        jax.clear_caches()

    # DECISION-EXACT agreement, no tolerated fraction (VERDICT r3 #3):
    # mega composes the same values-based _init_logic/_mon_scored_logic/
    # _gram_cd_core/_close_logic blocks as the XLA loop, and measured
    # agreement on this fixture is bit-exact across ALL seg_meta fields
    # in both variogram modes (tools/mega_diag.py) — the old >=98%/2e-4
    # envelope was stale conservatism from the pre-shared-logic kernel.
    # Segment counts, processing masks, and the day-valued decisions
    # (sday/eday/bday) plus curqa/nobs must be EQUAL on every pixel;
    # float diagnostics (chprob col 3, rmse, mag) get a tight envelope
    # so the pin survives a platform whose compiled accumulation order
    # differs in the last ulp without weakening any decision.
    np.testing.assert_array_equal(gn, rn)
    np.testing.assert_array_equal(np.asarray(got.mask), np.asarray(ref.mask))
    m_r, m_g = np.asarray(ref.seg_meta), np.asarray(got.seg_meta)
    np.testing.assert_array_equal(m_g[..., [0, 1, 2, 4, 5]],
                                  m_r[..., [0, 1, 2, 4, 5]])   # days/qa/nobs
    np.testing.assert_allclose(m_g[..., 3], m_r[..., 3], atol=1e-5)  # chprob
    # rmse is a float diagnostic, not a decision: the two routes reduce
    # the residual sums in different orders (measured max rel diff
    # 1.8e-5 under conftest x64; decisions above are still exact).
    np.testing.assert_allclose(np.asarray(got.seg_rmse),
                               np.asarray(ref.seg_rmse), rtol=1e-4)
    # mag is a median over scored residuals: an ulp input difference can
    # flip WHICH element lands in the median slot when two are nearly
    # equal, so the output jumps by the inter-element gap (measured: 7 of
    # 28000 elements, max 5.6e-3 on noise-scale values), not an ulp.
    np.testing.assert_allclose(np.asarray(got.seg_mag),
                               np.asarray(ref.seg_mag), rtol=5e-3, atol=1e-2)
    np.testing.assert_allclose(
        np.asarray(got.vario), np.asarray(ref.vario), rtol=1e-6)


@pytest.mark.slow  # ~60s interpret-mode run; tier-1 (-m 'not slow') keeps test_detect_mega_matches_batch_core as the mega-route parity rung
def test_detect_mega_sentinel2_and_capacity(monkeypatch):
    """Band-layout genericity + the overflow contract on the mega route:
    the 12-band Sentinel-2 kernel (different detection/tmask sets, no
    thermal) reproduces the XLA loop, and a deliberately tiny
    max_segments still COUNTS every close (writes past capacity drop) so
    detect_packed's capacity retry can see the overflow."""
    from firebird_tpu.ccd import synthetic
    from firebird_tpu.ccd.sensor import SENTINEL2

    rng = np.random.default_rng(13)
    C, P, T = 1, 96, 64
    B = SENTINEL2.n_bands
    t = np.stack([np.sort(rng.integers(737000, 737000 + 5500, T)).astype(
        np.float64) for _ in range(C)])
    X = np.stack([harmonic.design_matrix(t[c], t[c, 0], params.MAX_COEFS)
                  for c in range(C)])
    Xt_full = np.stack([harmonic.design_matrix(t[c], t[c, 0],
                                               params.TMASK_COEFS + 1)
                        for c in range(C)])
    Xt = np.concatenate([Xt_full[:, :, :1], Xt_full[:, :, 2:]], -1)
    valid = np.ones((C, T), bool)
    Y = (rng.integers(400, 3000, (C, 1, P, 1))
         + rng.normal(0, 50, (C, B, P, T)))
    for p_ in range(0, P, 2):       # a step change on half the pixels
        cpos = rng.integers(T // 3, 2 * T // 3)
        Y[0, :, p_, cpos:] += rng.uniform(400, 1200)
    Y = Y.astype(np.int16)
    qa = np.full((C, P, T), 1 << params.QA_CLEAR_BIT, np.int32)

    args = (jnp.asarray(X, jnp.float32), jnp.asarray(Xt, jnp.float32),
            jnp.asarray(t, jnp.float32), jnp.asarray(valid),
            jnp.asarray(Y), jnp.asarray(qa))
    kw = dict(wcap=24, dtype=jnp.float32, sensor=SENTINEL2)

    ref = kernel._detect_batch_core(*args, **kw)
    rn = np.asarray(ref.n_segments)

    monkeypatch.setenv("FIREBIRD_PALLAS", "mega")
    jax.clear_caches()
    try:
        got = kernel._detect_batch_core(*args, **kw)
        gn = np.asarray(got.n_segments)
        # capacity 1: closes past the first must still be COUNTED even
        # though their rows drop (the overflow-retry contract)
        tiny = kernel._detect_batch_core(*args, max_segments=1, **kw)
        tn = np.asarray(tiny.n_segments)
    finally:
        jax.clear_caches()

    assert np.mean(rn != gn) <= 0.02, np.mean(rn != gn)
    same = rn == gn
    m_r, m_g = np.asarray(ref.seg_meta), np.asarray(got.seg_meta)
    agree = np.isclose(m_r, m_g, atol=2e-4).all(-1).all(-1)[same].mean()
    assert agree >= 0.98, agree
    np.testing.assert_array_equal(tn, gn)          # counts don't saturate
    # the one in-capacity row equals the full run's first row
    np.testing.assert_allclose(
        np.asarray(tiny.seg_meta)[:, :, 0], m_g[:, :, 0], atol=1e-6)


@pytest.mark.slow  # ~30-60s interpret-mode run; tier-1 (-m 'not slow') budget keeps the faster per-kernel parity rungs instead
def test_mega_inside_sharded_detect(monkeypatch):
    """The sharded production path (shard_map over the mesh) composes
    with the whole-loop mega kernel: each shard runs its own
    single-device pallas_call (grid over its chip shard x pixel blocks),
    so no SPMD partitioning rule is needed.  f32: the mega route is
    gated f32-only, so an f64 dispatch would silently fall back to the
    XLA loop and make this test vacuous."""
    from firebird_tpu.ingest import SyntheticSource, pack
    from firebird_tpu.ingest.packer import PackedChips
    from firebird_tpu.parallel import make_mesh
    from firebird_tpu.parallel.mesh import detect_sharded

    src = SyntheticSource(seed=21, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    p = pack([src.chip(100 + 3000 * i, 200) for i in range(2)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :48, :], qas=p.qas[:, :48, :],
                    n_obs=p.n_obs, sensor=p.sensor)
    mesh = make_mesh(n_devices=2)
    ref = detect_sharded(p, mesh, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_PALLAS", "mega")
    jax.clear_caches()
    try:
        got = detect_sharded(p, mesh, dtype=jnp.float32)
    finally:
        jax.clear_caches()
    np.testing.assert_array_equal(np.asarray(got.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_allclose(np.asarray(got.seg_meta),
                               np.asarray(ref.seg_meta), atol=2e-4)


# ---------------------------------------------------------------------------
# Per-block skip guards (active-lane compaction, ISSUE 6): a block with
# no active lane must cost only its predicate + zero-fill, and a guarded
# call must agree with the unguarded one everywhere the caller reads.
# ---------------------------------------------------------------------------

def test_fit_guard_skips_dead_blocks_bit_identical(monkeypatch):
    """lasso_fit with an active mask whose trailing blocks are all dead
    (the post-compaction layout): guarded output equals unguarded on
    every lane — dead lanes carry all-zero windows, whose computed fit
    IS zero, so the skip fill is exact."""
    from firebird_tpu.ccd import harmonic

    # Narrow blocks keep the two-block interpret run tier-1 cheap.
    monkeypatch.setattr(pallas_ops, "fit_block_p", lambda *a: 128)
    rng = np.random.default_rng(8)
    T, B, K = 40, 7, params.MAX_COEFS
    BP = pallas_ops.fit_block_p(T, B, 2)
    P = 2 * BP                     # two blocks; block 1 fully dead
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], K), jnp.float32)
    Yt = jnp.asarray(rng.integers(0, 5000, (B, T, P)), jnp.int16)
    active = np.zeros(P, bool)
    active[: BP // 2] = True       # dense prefix, as compaction leaves it
    w = jnp.asarray(
        (rng.random((P, T)) < 0.8) & active[:, None], jnp.float32)
    mask = jnp.ones((P, K), bool)
    ref = pallas_ops.lasso_fit(Yt, w, X, mask, interpret=True)
    got = pallas_ops.lasso_fit(Yt, w, X, mask,
                               active=jnp.asarray(active), interpret=True)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # the skipped block really wrote zeros
    assert (np.asarray(got[0])[BP:] == 0).all()


def test_guarded_fit_inside_shard_map(monkeypatch):
    """The guarded kernels' per-block count operand composes with
    shard_map the same way the kernels themselves do (each shard runs
    its own single-device Mosaic call — the cnt-ref BlockSpec included):
    chip-sharded guarded lasso_fit equals the per-chip direct calls, and
    an all-dead shard still writes zeros through its guard."""
    from jax.sharding import PartitionSpec

    from firebird_tpu.ccd import harmonic
    from firebird_tpu.parallel import make_mesh

    monkeypatch.setattr(pallas_ops, "fit_block_p", lambda *a: 128)
    rng = np.random.default_rng(10)
    T, B, K = 40, 7, params.MAX_COEFS
    BP = pallas_ops.fit_block_p(T, B, 2)
    P, D = 2 * BP, 2               # two blocks per chip, two shards
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], K), jnp.float32)
    Yt = jnp.asarray(rng.integers(0, 5000, (D, B, T, P)), jnp.int16)
    active = np.zeros((D, P), bool)
    active[0, : BP // 2] = True    # shard 0: dense prefix
    w = jnp.asarray((rng.random((D, P, T)) < 0.8) & active[..., None],
                    jnp.float32)
    mask = jnp.ones((D, P, K), bool)
    act = jnp.asarray(active)

    mesh = make_mesh(n_devices=D)
    spec = PartitionSpec("data")

    def local(Ytc, wc, mc, ac):
        out = pallas_ops.lasso_fit(Ytc[0], wc[0], X, mc[0], active=ac[0],
                                   interpret=True)
        return jax.tree_util.tree_map(lambda o: o[None], out)

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        fn = sm(local, mesh=mesh, in_specs=(spec,) * 4, out_specs=spec,
                check_vma=False)
    else:  # jax < 0.5: experimental module, check_rep spelling
        from jax.experimental.shard_map import shard_map as sm_exp

        fn = sm_exp(local, mesh=mesh, in_specs=(spec,) * 4,
                    out_specs=spec, check_rep=False)
    got = fn(Yt, w, mask, act)
    for d in range(D):
        ref = pallas_ops.lasso_fit(Yt[d], w[d], X, mask[d],
                                   active=act[d], interpret=True)
        for r, g in zip(ref, (got[0][d], got[1][d])):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # shard 1 has no active lane: every block skipped, zeros written
    assert (np.asarray(got[0])[1] == 0).all()


def test_monitor_scored_guard_matches_on_active_lanes():
    """monitor_chain_scored under a dense-prefix in_mon mask: guarded ==
    unguarded on every lane the caller uses (in_mon lanes; the rest are
    masked downstream, kernel._mon_block)."""
    from firebird_tpu.ccd import harmonic
    from firebird_tpu.ccd.sensor import chi2_thresholds

    rng = np.random.default_rng(9)
    T, nb, K = 48, 5, params.MAX_COEFS
    BP = pallas_ops.scored_block_p(T, nb, 2)
    P = 2 * BP
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], K), jnp.float32)
    Yd = jnp.asarray(rng.integers(0, 5000, (nb, T, P)), jnp.int16)
    coefs = jnp.asarray(rng.normal(0, 1, (P, nb, K)), jnp.float32)
    dden = jnp.asarray(rng.uniform(50, 200, (P, nb)), jnp.float32)
    alive = jnp.asarray(rng.random((P, T)) < 0.8)
    included = jnp.asarray(rng.random((P, T)) < 0.3)
    cur_k = jnp.asarray(rng.integers(0, T // 2, P), jnp.int32)
    nlast = jnp.asarray(rng.integers(12, 40, P), jnp.int32)
    in_mon = jnp.asarray(np.arange(P) < BP // 3)   # dense prefix
    ct, ot = chi2_thresholds(nb)
    kw = dict(change_thr=float(ct), outlier_thr=float(ot), interpret=True)
    ref = pallas_ops.monitor_chain_scored(Yd, coefs, dden, X, alive,
                                          included, cur_k, nlast, in_mon,
                                          **kw)
    got = pallas_ops.monitor_chain_scored(Yd, coefs, dden, X, alive,
                                          included, cur_k, nlast, in_mon,
                                          active=in_mon, **kw)
    use = np.asarray(in_mon)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(ref[k])[use],
                                      np.asarray(got[k])[use], err_msg=k)
        # the dead trailing block wrote zeros/False
        assert (np.asarray(got[k])[BP:] == 0).all(), k


@pytest.mark.slow  # ~35s: two interpret-mode traces of the W-unrolled
# init body; the tier-1 guard coverage stays with the fit/monitor/cd
# rungs, which exercise the same _when_active plumbing.
def test_init_window_guard_passes_alive_through(monkeypatch):
    """init_window's skipped blocks mirror kernel._init_zeros: flags and
    indices zero, alive passed through untouched."""
    from firebird_tpu.ccd import harmonic
    from firebird_tpu.ccd.sensor import LANDSAT_ARD

    # Narrow blocks + a small W keep the two-block interpret run tier-1
    # cheap: the init body unrolls per window slot, and interpret-mode
    # cost is dominated by tracing that body, not by lanes.
    monkeypatch.setattr(pallas_ops, "init_block_p", lambda *a: 128)
    rng = np.random.default_rng(10)
    T, B, K, NT, W = 32, 7, params.MAX_COEFS, params.TMASK_COEFS, 8
    BP = pallas_ops.init_block_p(T, W, B, 2)
    P = 2 * BP
    t = np.sort(rng.integers(729000, 730500, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, t[0], K), jnp.float32)
    Xt_full = harmonic.design_matrix(t, t[0], params.TMASK_COEFS + 1)
    Xt = jnp.asarray(np.concatenate([Xt_full[:, :1], Xt_full[:, 2:]], 1),
                     jnp.float32)
    Yt = jnp.asarray(rng.integers(0, 5000, (B, T, P)), jnp.int16)
    vario = jnp.asarray(rng.uniform(20, 100, (P, B)), jnp.float32)
    alive = jnp.asarray(rng.random((P, T)) < 0.7)
    cur_i = jnp.asarray(rng.integers(0, T // 2, P), jnp.int32)
    in_init = jnp.asarray(np.arange(P) < BP // 2)  # block 1 fully dead
    kw = dict(W=W, sensor=LANDSAT_ARD, interpret=True)
    ref = pallas_ops.init_window(alive, cur_i, in_init,
                                 jnp.asarray(t, jnp.float32), X, Xt, Yt,
                                 vario, **kw)
    got = pallas_ops.init_window(alive, cur_i, in_init,
                                 jnp.asarray(t, jnp.float32), X, Xt, Yt,
                                 vario, active=in_init, **kw)
    use = np.asarray(in_init)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(ref[k])[use],
                                      np.asarray(got[k])[use], err_msg=k)
    # the skipped block passes alive through (Tmask removes nothing for
    # non-INIT lanes) and zeroes the flags
    np.testing.assert_array_equal(np.asarray(got["alive_init"])[BP:],
                                  np.asarray(alive)[BP:])
    assert not np.asarray(got["init_ok"])[BP:].any()


def test_lasso_cd_and_tmask_guards():
    """The remaining guarded kernels: all-dead calls fill exact zeros;
    mixed calls agree with unguarded on active lanes."""
    G, c, d, m = _systems(P=24, dtype=jnp.float64)
    dead = jnp.zeros(24, bool)
    z = pallas_ops.lasso_cd(G, jnp.zeros_like(c), d, m, active=dead,
                            interpret=True)
    assert (np.asarray(z) == 0).all()
    act = jnp.asarray(np.arange(24) < 9)
    ref = pallas_ops.lasso_cd(G, c, d, m, interpret=True)
    got = pallas_ops.lasso_cd(G, c, d, m, active=act, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref)[:9], np.asarray(got)[:9])

    rng = np.random.default_rng(12)
    P, W, nt, nb = 20, 16, params.TMASK_COEFS, 2
    Xtw = jnp.asarray(rng.normal(0, 1, (P, W, nt)), jnp.float32)
    Y2 = jnp.asarray(rng.normal(1000, 200, (P, nb, W)), jnp.float32)
    w = jnp.asarray(rng.random((P, W)) < 0.8, jnp.float32)
    v2 = jnp.asarray(rng.uniform(20, 80, (P, nb)), jnp.float32)
    ref = pallas_ops.tmask_bad(Xtw, Y2, w, v2, interpret=True)
    act = jnp.ones(P, bool)
    got = pallas_ops.tmask_bad(Xtw, Y2, w, v2, active=act, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    z = pallas_ops.tmask_bad(Xtw, Y2, w, v2, active=jnp.zeros(P, bool),
                             interpret=True)
    assert not np.asarray(z).any()
