"""The closed-form FLOP/roofline model (ccd/flops.py): internal
consistency, scaling laws, and a cross-check of the dominant term against
XLA's own cost analysis of the same algebra."""

import jax
import jax.numpy as jnp
import numpy as np

from firebird_tpu.ccd import flops, params
from firebird_tpu.ccd.sensor import LANDSAT_ARD, SENTINEL2


def test_round_flops_scaling():
    base = flops.round_flops(1000, 400, 120)["total"]
    # linear in P
    assert np.isclose(flops.round_flops(2000, 400, 120)["total"], 2 * base,
                      rtol=1e-6)
    # monotone in T and W
    assert flops.round_flops(1000, 800, 120)["total"] > base
    assert flops.round_flops(1000, 400, 240)["total"] > base
    # every group positive
    assert all(v > 0 for v in flops.round_flops(1000, 400, 120).values())


def test_detect_flops_composition():
    r = flops.round_flops(500, 300, 100)["total"]
    d = flops.detect_flops(500, 300, 100, rounds=20)
    assert d["total"] == r * 20 + flops.setup_flops(500, 300)
    assert np.isclose(d["per_pixel"], d["total"] / 500)


def test_sentinel2_costs_more_per_obs():
    """12 bands cost more arithmetic than 7 at the same shape."""
    l = flops.round_flops(1000, 400, 120, LANDSAT_ARD)["total"]
    s = flops.round_flops(1000, 400, 120, SENTINEL2)["total"]
    assert s > l


def test_gram_corr_term_matches_xla_cost_analysis():
    """The model's Lasso Gram+corr term (the dominant per-round matmuls,
    kernel.py:174-175) agrees with XLA's flop count for the same algebra
    to within fusion/bookkeeping noise."""
    P, T, B, K = 256, 128, 7, params.MAX_COEFS
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(T, K)), jnp.float32)
    XX = (X[:, :, None] * X[:, None, :]).reshape(T, K * K)
    Y = jnp.asarray(rng.normal(size=(P, B, T)), jnp.float32)
    w = jnp.asarray((rng.uniform(size=(P, T)) > 0.5), jnp.float32)

    def gram_corr(w, XX, Y, X):
        G = (w @ XX).reshape(-1, K, K)
        c = jnp.einsum("pbt,tc->pbc", Y * w[:, None, :], X)
        return G, c

    analysis = jax.jit(gram_corr).lower(w, XX, Y, X).compile().cost_analysis()
    xla_flops = analysis["flops"] if isinstance(analysis, dict) \
        else analysis[0]["flops"]
    model = 2.0 * P * T * K * K + 2.0 * P * B * T * K + P * B * T
    assert 0.5 * model <= xla_flops <= 1.5 * model, (xla_flops, model)


def test_peak_lookup():
    pk = flops.peak_for("TPU v5 lite")
    assert pk is not None and pk.bf16_flops == 197e12
    assert flops.peak_for("cpu") is None
    assert flops.peak_for("TPU v4") is not None


def test_bench_detail_shapes():
    d = flops.bench_detail(pixels_per_sec=1e4, P=80000, T=480, W=160,
                           S=8, rounds=40.0, device_kind="TPU v5 lite")
    for key in ("model_flops_per_pixel", "arithmetic_intensity",
                "achieved_tflops", "mfu_pct_vs_f32_peak",
                "compute_bound_pixels_per_sec", "hbm_bound_pixels_per_sec"):
        assert key in d and d[key] > 0, key
    # no peak entry for CPU: MFU keys absent, model keys still present
    c = flops.bench_detail(pixels_per_sec=100.0, P=80000, T=480, W=160,
                           S=8, rounds=40.0, device_kind="cpu")
    assert "mfu_pct_vs_f32_peak" not in c
    assert c["model_flops_per_pixel"] == d["model_flops_per_pixel"]


def test_mixed_block_models_pass_counts_not_new_flops():
    """FIREBIRD_MIXED_PRECISION changes the MXU schedule, not the useful
    arithmetic: every shared term (and total) is identical with and
    without mixed=True, and the mixed sub-dict models exactly the
    dot-stage pass trade (gram 6->2, corr 6->3, bf16 operands)."""
    f32 = flops.round_flops(1000, 400, 120)
    mx = flops.round_flops(1000, 400, 120, mixed=True)
    assert {k: v for k, v in mx.items() if k != "mixed"} == f32
    md = mx["mixed"]
    assert (md["mxu_passes_f32"], md["mxu_passes_gram"],
            md["mxu_passes_corr"]) == (6, 2, 3)
    assert md["gram_operand_bytes_ratio"] == 0.5
    g, c = md["gram_dot_flops"], md["corr_dot_flops"]
    assert g > 0 and c > 0
    assert md["dot_stage_speedup_model"] == round(
        6.0 * (g + c) / (2.0 * g + 3.0 * c), 2)
    # the schedule trade is strictly a win and bounded by the pass ratios
    assert 2.0 < md["dot_stage_speedup_model"] < 3.0


def test_round_bytes_is_mixed_invariant():
    """The HBM model must NOT move under mixed: the wire spectra stream
    int16 either way and the bf16 operands live at the VMEM->MXU
    boundary (round_bytes' docstring is the written argument)."""
    for pallas in ((), ("fit",), ("fit", "init", "score")):
        a = flops.round_bytes(1000, 400, 120, 4, 4, rounds=12.0,
                              pallas=pallas)
        b = flops.round_bytes(1000, 400, 120, 4, 4, rounds=12.0,
                              pallas=pallas, mixed=True)
        assert a == b
