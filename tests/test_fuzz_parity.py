"""Randomized kernel-vs-oracle fuzz parity.

The deterministic parity tests (test_ccd_kernel.py) cover curated
scenarios; this harness sweeps adversarial *random* ones — mixed QA bit
patterns, duplicate acquisition dates, sparse and short archives, multiple
step changes of varying magnitude, ramps, spikes, range-violating values —
and asserts the TPU kernel reproduces the NumPy oracle decision-for-
decision on every generated pixel.  Seeds are fixed, so failures are
reproducible; any divergence is a real spec mismatch, not noise (both
sides run float64 with the same Gram/coordinate-descent formulation).

Date grids are sized so their bucketed time axes collide (pack bucket=64),
keeping the number of distinct XLA compiles at two for the whole sweep.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from firebird_tpu.ccd import detect, kernel, params, synthetic
from firebird_tpu.ccd.sensor import LANDSAT_ARD, SENTINEL2
from firebird_tpu.ingest import pixel_timeseries
from firebird_tpu.ingest.packer import PackedChips


def _unwrap_chip(seg):
    """Batched device ChipSegments -> chip 0 as host arrays."""
    return kernel.chip_slice(seg, 0, to_host=True)


def _assert_structural(o, k, i):
    """Oracle record vs kernel record: every decision-level field."""
    assert k["procedure"] == o["procedure"], i
    assert len(o["change_models"]) == len(k["change_models"]), i
    assert o["processing_mask"] == k["processing_mask"], i
    for om, km in zip(o["change_models"], k["change_models"]):
        for f in ("start_day", "end_day", "break_day", "curve_qa",
                  "observation_count"):
            assert om[f] == km[f], i
        assert om["change_probability"] == pytest.approx(
            km["change_probability"], abs=1e-6), i

QA = {
    "clear": np.uint16(1 << params.QA_CLEAR_BIT),
    "water": np.uint16(1 << params.QA_WATER_BIT),
    "shadow": np.uint16((1 << params.QA_SHADOW_BIT) | (1 << params.QA_CLOUD_BIT)),
    "snow": np.uint16(1 << params.QA_SNOW_BIT),
    "cloud": np.uint16(1 << params.QA_CLOUD_BIT),
    "fill": np.uint16(1 << params.QA_FILL_BIT),
}
N_PIXELS = 40


def _dates(start, end, cadence, drop, dup_frac, rng):
    t = synthetic.acquisition_dates(start, end, cadence, rng=rng,
                                    drop_frac=drop)
    if dup_frac > 0:
        dups = t[rng.random(t.shape[0]) < dup_frac]
        t = np.sort(np.concatenate([t, dups]))
    return t


def _fuzz_pixel(t, rng, special=None, sensor=None):
    """One adversarial (spectra [B,T], qa [T]) pair."""
    sensor = sensor or LANDSAT_ARD
    B, T = sensor.n_bands, t.shape[0]
    noise = rng.uniform(10.0, 60.0)
    slope = rng.uniform(-100.0, 100.0)
    means, amps = synthetic.means_amps(sensor)
    Y = synthetic.harmonic_series(t, rng, means=means, amps=amps,
                                  slope_per_year=slope, noise=noise)

    # 0-3 step changes at random interior dates, random band subsets,
    # deltas spanning sub-threshold to obvious.
    for _ in range(rng.integers(0, 4)):
        c = rng.integers(T // 6, 5 * T // 6)
        delta = rng.uniform(150.0, 1500.0) * rng.choice([-1.0, 1.0])
        bands = rng.random(B) < rng.uniform(0.4, 1.0)
        Y[bands, c:] += delta

    # spikes: short transients the Tmask/outlier screens should absorb
    for _ in range(rng.integers(0, 3)):
        s = rng.integers(0, T)
        width = rng.integers(1, 3)
        Y[:, s:s + width] += rng.choice([-3000.0, 3000.0])

    # QA: per-pixel category mix
    p_clear = rng.uniform(0.3, 1.0)
    rest = 1.0 - p_clear
    probs = np.array([p_clear, 0.1 * rest, 0.35 * rest, 0.2 * rest,
                      0.25 * rest, 0.1 * rest])
    cats = rng.choice(["clear", "water", "shadow", "snow", "cloud", "fill"],
                      size=T, p=probs / probs.sum())
    if special == "snowy":       # permanent-snow procedure territory
        cats = rng.choice(["snow", "clear", "fill"], size=T,
                          p=[0.85, 0.05, 0.10])
    elif special == "cloudy":    # insufficient-clear territory
        cats = rng.choice(["cloud", "shadow", "clear"], size=T,
                          p=[0.6, 0.25, 0.15])
    elif special == "fill":      # no-data
        cats = np.full(T, "fill")
    elif special == "short":     # clear count straddles MEOW_SIZE
        cats = np.full(T, "cloud")
        n = params.MEOW_SIZE + int(rng.integers(-2, 3))
        cats[rng.choice(T, size=min(n, T), replace=False)] = "clear"
    qa = np.array([QA[c] for c in cats], dtype=np.uint16)

    # range violations on a few clear obs (kernel must drop like oracle)
    viol = rng.random(T) < 0.02
    Y[:, viol] = rng.choice([-30000.0, 20000.0])
    Y[:, cats == "fill"] = params.FILL_VALUE
    return Y, qa


def _pack_pixels(t, Ys, qas, bucket=64, sensor=None):
    P, T = len(Ys), t.shape[0]
    Tb = -bucket * (-T // bucket)
    spectra = np.stack([np.asarray(Y, np.int16) for Y in Ys])
    spectra = np.pad(spectra.transpose(1, 0, 2)[None],
                     ((0, 0), (0, 0), (0, 0), (0, Tb - T)),
                     constant_values=params.FILL_VALUE)
    qa = np.pad(np.stack(qas)[None], ((0, 0), (0, 0), (0, Tb - T)),
                constant_values=int(QA["fill"]))
    return PackedChips(cids=np.zeros((1, 2), np.int64),
                       dates=np.pad(t[None], ((0, 0), (0, Tb - T))).astype(np.int32),
                       spectra=spectra, qas=qa,
                       n_obs=np.array([T], np.int32),
                       sensor=sensor or LANDSAT_ARD)


GRIDS = [
    # (start, end, cadence_days, drop_frac, dup_frac, seed) — first three
    # bucket to T=128, the short one to T=64: two compiles total.
    ("1995-01-01", "2000-01-01", 16, 0.15, 0.05, 101),
    ("1999-01-01", "2003-01-01", 12, 0.10, 0.10, 202),
    ("1990-01-01", "2000-01-01", 16, 0.50, 0.00, 303),
    ("2000-01-01", "2002-06-01", 16, 0.00, 0.08, 404),
]
SPECIALS = {0: "snowy", 1: "cloudy", 2: "fill", 3: "short", 4: "short"}


def test_fuzz_sentinel2_structural_parity():
    """The multi-sensor claim at decision level: the 12-band Sentinel-2
    kernel (no thermal, different detection dof -> different chi2
    thresholds) reproduces the sensor-generic float64 oracle
    (reference.detect_sensor) on adversarial pixels."""
    from firebird_tpu.ccd.reference import detect_sensor

    rng = np.random.default_rng(77)
    t = _dates("2018-01-01", "2022-01-01", 10, 0.2, 0.05, rng)
    n_px = 24
    pixels = [_fuzz_pixel(t, rng, special=SPECIALS.get(i), sensor=SENTINEL2)
              for i in range(n_px)]
    p = _pack_pixels(t, [Y for Y, _ in pixels], [q for _, q in pixels],
                     sensor=SENTINEL2)
    seg = _unwrap_chip(kernel.detect_packed(p, dtype=jnp.float64))
    dates = p.dates[0][: int(p.n_obs[0])]
    T = dates.shape[0]
    for i in range(n_px):
        o = detect_sensor(dates, np.asarray(p.spectra[0, :, i, :T],
                                            np.float64),
                          p.qas[0, i, :T], SENTINEL2)
        k = kernel.segments_to_records(seg, dates, i, sensor=SENTINEL2)
        _assert_structural(o, k, i)


@pytest.mark.parametrize("grid", GRIDS, ids=[str(g[5]) for g in GRIDS])
def test_fuzz_structural_parity(grid):
    start, end, cad, drop, dup, seed = grid
    rng = np.random.default_rng(seed)
    t = _dates(start, end, cad, drop, dup, rng)
    pixels = [_fuzz_pixel(t, rng, special=SPECIALS.get(i))
              for i in range(N_PIXELS)]
    p = _pack_pixels(t, [Y for Y, _ in pixels], [q for _, q in pixels])
    seg = _unwrap_chip(kernel.detect_packed(p, dtype=jnp.float64))
    dates = p.dates[0][: int(p.n_obs[0])]

    for i in range(N_PIXELS):
        o = detect(**pixel_timeseries(p, 0, i))
        k = kernel.segments_to_records(seg, dates, i)
        _assert_structural(o, k, i)
        # Numeric spot checks on a subset.  Tolerances: the two sides build
        # bit-identical Gram *terms* but sum them in different orders
        # (matmul over T vs gathered-window sum), and the fixed-iteration
        # Lasso CD amplifies that roundoff on ill-conditioned fits — two
        # 36-grid x 40-pixel sweeps measured coef diffs up to ~5e-6 and
        # magnitude diffs up to ~2.5e-4 relative (near-zero residual
        # medians inherit the coef noise; break dates were exact on all
        # 2880 pixels).  Derived quantities cannot be tighter than the
        # coef tolerance below.
        if i % 6:
            continue
        for om, km in zip(o["change_models"], k["change_models"]):
            for band in params.BAND_NAMES:
                assert km[band]["rmse"] == pytest.approx(
                    om[band]["rmse"], rel=5e-4, abs=1e-4), i
                assert km[band]["magnitude"] == pytest.approx(
                    om[band]["magnitude"], rel=5e-4, abs=1e-4), i
                for a, b in zip(om[band]["coefficients"],
                                km[band]["coefficients"]):
                    assert b == pytest.approx(a, rel=1e-4, abs=1e-3), i
