"""Behavioral tests for the CCDC NumPy oracle.

The reference repo has no algorithm-accuracy tests (the algorithm lived in
the external pyccd package); these pin the behavior of our spec on series
with known ground truth.
"""

import numpy as np
import pytest

from firebird_tpu.ccd import detect, params, synthetic
from firebird_tpu.utils import dates as dt


@pytest.fixture(scope="module")
def t():
    return synthetic.acquisition_dates("1995-01-01", "2015-01-01", 16)


def test_stable_series_one_segment(t, rng=None):
    rng = np.random.default_rng(7)
    Y = synthetic.harmonic_series(t, rng)
    res = detect(**synthetic.pixel(t, Y))
    assert res["procedure"] == "standard"
    assert len(res["change_models"]) == 1
    m = res["change_models"][0]
    assert m["curve_qa"] == params.CURVE_QA_START | params.CURVE_QA_END
    assert m["start_day"] == int(t[0])
    assert m["end_day"] == int(t[-1])
    assert m["change_probability"] <= 1 / params.PEEK_SIZE
    # Model recovered: nir mean ~2500, annual cos amplitude ~400.
    nir = m["nir"]
    fitted_mean = nir["intercept"] + nir["coefficients"][0] * (t[0] + t[-1]) / 2
    assert abs(fitted_mean - 2500) < 100
    assert abs(nir["coefficients"][1] - 400) < 60
    assert nir["rmse"] < 60
    # Essentially every clear obs participates.
    assert m["observation_count"] >= t.shape[0] - 2


def test_step_change_two_segments(t):
    rng = np.random.default_rng(8)
    Y = synthetic.harmonic_series(t, rng)
    Y = synthetic.with_step_change(Y, t, "2005-06-01", delta=800.0)
    res = detect(**synthetic.pixel(t, Y))
    assert len(res["change_models"]) == 2
    first, second = res["change_models"]
    change_ord = dt.to_ordinal("2005-06-01")
    # Break lands on the first obs at/after the change date.
    expected_break = int(t[t >= change_ord][0])
    assert first["break_day"] == expected_break
    assert first["change_probability"] == 1.0
    assert first["curve_qa"] == params.CURVE_QA_START
    assert second["curve_qa"] == params.CURVE_QA_END
    assert second["start_day"] == expected_break
    # Magnitude reflects the step (nir residual ~ +800).
    assert abs(first["nir"]["magnitude"] - 800) < 150
    # Second segment fits the shifted level.
    m2 = second["nir"]
    mid2 = (second["start_day"] + second["end_day"]) / 2
    assert abs(m2["intercept"] + m2["coefficients"][0] * mid2 - 3300) < 120


def test_single_outlier_is_masked_not_break(t):
    rng = np.random.default_rng(9)
    Y = synthetic.harmonic_series(t, rng)
    spike = t.shape[0] // 2
    Y[:, spike] += 4000.0  # a cloud-like spike in every band
    res = detect(**synthetic.pixel(t, Y))
    assert len(res["change_models"]) == 1
    assert res["processing_mask"][spike] == 0


def test_all_fill_no_models():
    t = np.array([723868, 724404, 731205, 734973])
    Y = np.full((7, 4), params.FILL_VALUE, dtype=np.float64)
    qa = np.full(4, synthetic.QA_FILL, dtype=np.uint16)
    res = detect(**synthetic.pixel(t, Y, qa))
    assert res["change_models"] == []
    assert res["processing_mask"] == [0, 0, 0, 0]
    assert res["procedure"] == "no-data"


def test_reference_fixture_element_shape():
    """The reference's canonical smoke element: 4 obs, all fill values,
    qas=1 (fill bit) — test/__init__.py:37-46."""
    res = detect(
        dates=[734973, 731205, 724404, 723868],
        blues=np.full(4, -9999, np.int16), greens=np.full(4, -9999, np.int16),
        reds=np.full(4, -9999, np.int16), nirs=np.full(4, -9999, np.int16),
        swir1s=np.full(4, -9999, np.int16), swir2s=np.full(4, -9999, np.int16),
        thermals=np.full(4, -9999, np.int16),
        qas=np.array([1, 1, 1, 1], np.uint16))
    assert res["change_models"] == []
    assert len(res["processing_mask"]) == 4


def test_snow_procedure(t):
    rng = np.random.default_rng(10)
    Y = synthetic.harmonic_series(t, rng)
    qa = np.full(t.shape[0], synthetic.QA_SNOW, dtype=np.uint16)
    qa[: t.shape[0] // 10] = synthetic.QA_CLEAR  # <25% clear, >75% snow
    res = detect(**synthetic.pixel(t, Y, qa))
    assert res["procedure"] == "permanent-snow"
    assert len(res["change_models"]) == 1
    assert res["change_models"][0]["curve_qa"] == params.CURVE_QA_PERSIST_SNOW
    assert res["change_models"][0]["change_probability"] == 0.0


def test_insufficient_clear_procedure(t):
    rng = np.random.default_rng(11)
    Y = synthetic.harmonic_series(t, rng)
    qa = np.full(t.shape[0], synthetic.QA_CLOUD, dtype=np.uint16)
    res = detect(**synthetic.pixel(t, Y, qa))
    assert res["procedure"] == "insufficient-clear"
    assert len(res["change_models"]) == 1
    assert res["change_models"][0]["curve_qa"] == params.CURVE_QA_INSUF_CLEAR


def test_input_order_invariance(t):
    """The data plane delivers newest-first (ccdc/timeseries.py:104-115);
    results must not depend on input order and the mask must align to the
    input order."""
    rng = np.random.default_rng(12)
    Y = synthetic.harmonic_series(t, rng)
    spike = t.shape[0] // 2
    Y[:, spike] += 4000.0
    fwd = detect(**synthetic.pixel(t, Y))
    rev = detect(**synthetic.pixel(t[::-1], Y[:, ::-1]))
    assert fwd["change_models"] == rev["change_models"]
    assert rev["processing_mask"] == fwd["processing_mask"][::-1]


def test_segment_record_contract(t):
    """Fields consumed by the format layer (ccdc/pyccd.py:106-148)."""
    rng = np.random.default_rng(13)
    Y = synthetic.harmonic_series(t, rng)
    m = detect(**synthetic.pixel(t, Y))["change_models"][0]
    assert {"start_day", "end_day", "break_day", "observation_count",
            "change_probability", "curve_qa"} <= set(m.keys())
    for band in params.BAND_NAMES:
        assert {"magnitude", "rmse", "coefficients", "intercept"} == set(m[band].keys())
        assert len(m[band]["coefficients"]) == 7
