"""Adjusted-variogram mode (docs/DIVERGENCE.md #1): the reconstructed
lcmap-pyccd ``adjusted_variogram`` rule — successive-difference pairs
restricted to >VARIOGRAM_GAP_DAYS apart, plain-madogram fallback —
implemented identically in the f64 oracle (reference.variogram) and the
batched kernel (kernel._variogram), selectable via FIREBIRD_VARIOGRAM.

The reference pins lcmap-pyccd 2018.03.12 (setup.py:32) whose source is
unreachable offline; the rule here is reconstructed from the public
package's algorithm (the 'ncompare' dense multi-sensor correction).
These tests pin oracle<->kernel agreement in BOTH modes and the rule's
expected direction, so whichever mode ships, the two implementations
cannot drift apart.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firebird_tpu.ccd import kernel, params, synthetic
from firebird_tpu.ccd.reference import variogram as oracle_variogram


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("FIREBIRD_VARIOGRAM", raising=False)
    monkeypatch.delenv("FIREBIRD_PALLAS", raising=False)


def _series(seed, P=23, B=7, T=90, dup_frac=0.3):
    """Random masked series on a dense grid with near-coincident pairs
    (the case where adjusted != plain)."""
    rng = np.random.default_rng(seed)
    t = np.sort(rng.integers(726000, 726000 + 16 * T, T)).astype(np.float64)
    dups = t[rng.random(T) < dup_frac] + rng.integers(1, 9)
    t = np.sort(np.concatenate([t, dups]))[:T]
    Y = rng.normal(1200, 300, (P, B, T))
    usable = rng.random((P, T)) < 0.8
    usable[:, :2] = True
    return t, Y, usable


@pytest.mark.parametrize("adjusted", [False, True])
def test_kernel_matches_oracle_variogram(adjusted):
    """kernel._variogram == reference.variogram per pixel (f64), both
    modes, on dup-heavy grids where the pair selections differ."""
    t, Y, usable = _series(11)
    got = np.asarray(kernel._variogram(
        jnp.asarray(Y), jnp.asarray(usable), t=jnp.asarray(t),
        adjusted=adjusted))
    for p in range(Y.shape[0]):
        idx = np.flatnonzero(usable[p])
        want = oracle_variogram(t[idx], Y[p][:, idx], adjusted=adjusted)
        np.testing.assert_allclose(got[p], want, rtol=1e-12, atol=1e-12,
                                   err_msg=f"pixel {p} adjusted={adjusted}")


def test_adjusted_excludes_near_coincident_pairs():
    """On a grid whose only small |diff| pairs are the near-coincident
    duplicates, the adjusted variogram must exceed the plain one (the
    rule exists to stop L7+L8-style pairs cratering the denominator)."""
    rng = np.random.default_rng(7)
    T = 80
    base = np.sort(rng.integers(726000, 726000 + 16 * T, T // 2)).astype(
        np.float64)
    t = np.sort(np.concatenate([base, base + 2.0]))      # every obs paired
    # seasonal-scale signal: big diffs across >30d gaps, tiny across 2d
    Y = 1000.0 + 400.0 * np.sin(2 * np.pi * t / 365.25)
    Y = np.tile(Y, (1, 7, 1)).reshape(1, 7, t.shape[0])
    usable = np.ones((1, t.shape[0]), bool)
    plain = np.asarray(kernel._variogram(
        jnp.asarray(Y), jnp.asarray(usable), t=jnp.asarray(t),
        adjusted=False))[0]
    adj = np.asarray(kernel._variogram(
        jnp.asarray(Y), jnp.asarray(usable), t=jnp.asarray(t),
        adjusted=True))[0]
    assert np.all(adj > plain)
    # and the oracle agrees on the direction
    o_plain = oracle_variogram(t, Y[0], adjusted=False)
    o_adj = oracle_variogram(t, Y[0], adjusted=True)
    assert np.all(o_adj > o_plain)


def test_adjusted_fallback_when_no_wide_pairs():
    """A burst archive (every gap < VARIOGRAM_GAP_DAYS) falls back to the
    plain pair set in both implementations."""
    rng = np.random.default_rng(3)
    T = 40
    t = np.cumsum(rng.integers(1, 20, T)).astype(np.float64) + 726000
    assert np.all(np.diff(t) <= params.VARIOGRAM_GAP_DAYS)
    Y = rng.normal(1500, 250, (5, 7, T))
    usable = np.ones((5, T), bool)
    a = np.asarray(kernel._variogram(jnp.asarray(Y), jnp.asarray(usable),
                                     t=jnp.asarray(t), adjusted=True))
    p = np.asarray(kernel._variogram(jnp.asarray(Y), jnp.asarray(usable),
                                     t=jnp.asarray(t), adjusted=False))
    np.testing.assert_array_equal(a, p)
    np.testing.assert_allclose(
        oracle_variogram(t, Y[0], adjusted=True),
        oracle_variogram(t, Y[0], adjusted=False), rtol=0, atol=0)


def test_detect_decision_parity_adjusted_mode(monkeypatch):
    """End-to-end: FIREBIRD_VARIOGRAM=adjusted routes the kernel's
    prologue through the adjusted rule and the detector still reproduces
    the oracle (same mode) decision-for-decision on a dup-heavy grid."""
    from firebird_tpu.ccd.reference import detect_sensor
    from firebird_tpu.ccd.sensor import LANDSAT_ARD
    from tests.test_fuzz_parity import (_assert_structural, _dates,
                                        _fuzz_pixel, _pack_pixels,
                                        _unwrap_chip)

    rng = np.random.default_rng(55)
    t = _dates("1996-01-01", "2003-01-01", 8, 0.1, 0.35, rng)
    n_px = 16
    pixels = [_fuzz_pixel(t, rng) for _ in range(n_px)]
    p = _pack_pixels(t, [Y for Y, _ in pixels], [q for _, q in pixels])

    monkeypatch.setenv("FIREBIRD_VARIOGRAM", "adjusted")
    jax.clear_caches()     # the mode is read at trace time
    try:
        seg = _unwrap_chip(kernel.detect_packed(p, dtype=jnp.float64))
    finally:
        jax.clear_caches()  # don't leak adjusted-mode traces to other tests
    dates = p.dates[0][: int(p.n_obs[0])]
    T = dates.shape[0]
    for i in range(n_px):
        o = detect_sensor(dates, np.asarray(p.spectra[0, :, i, :T],
                                            np.float64),
                          p.qas[0, i, :T], LANDSAT_ARD,
                          adjusted_variogram=True)
        k = kernel.segments_to_records(seg, dates, i)
        _assert_structural(o, k, i)
