"""Registry-driven band discovery (ingest/registry.py).

Golden-checked against the reference's recorded Chipmunk ``/registry``
response (test/data/registry_response.json — 97 entries) when the reference
tree is available: the tag-derived ubid maps must reproduce the
Collection-01 tables exactly.  Synthetic registries cover new-sensor
reconfiguration, wire dtypes, chip geometry, and the fallback path.
"""

import base64
import json
from pathlib import Path
from urllib.parse import parse_qs, urlparse

import numpy as np
import pytest

from firebird_tpu.ingest import ChipmunkSource
from firebird_tpu.ingest.registry import Registry
from firebird_tpu.ingest.sources import ARD_UBIDS, AUX_UBIDS

REF_REGISTRY = Path(__file__).parent / "data" / "recorded" \
    / "registry_response.json"


def _lower(ubids):
    return tuple(u.lower() for u in ubids)


@pytest.fixture(scope="module")
def ref_registry():
    if not REF_REGISTRY.exists():
        pytest.skip("reference registry fixture not available")
    return Registry(json.loads(REF_REGISTRY.read_text()))


class TestGoldenVsReference:
    """Tag rules must reproduce the hardcoded Collection-01 tables from the
    recorded service response (case differs: registry uses upper ubids)."""

    def test_ard_ubids(self, ref_registry):
        # Ordered comparison: _band_series merges first-writer-wins across
        # platforms, so the derived platform priority (mission order,
        # lt04 first) must match the built-in tables exactly.
        ard = ref_registry.ard_ubids()
        assert set(ard) == set(ARD_UBIDS)
        for band, expect in ARD_UBIDS.items():
            assert _lower(ard[band]) == _lower(expect), band

    def test_thermal_prefers_lowest_band(self, ref_registry):
        # LC08 exposes BTB10 + BTB11; merlin's profile (and the reference's
        # recorded chips) use btb10.
        thermals = _lower(ref_registry.ard_ubids()["thermals"])
        assert "lc08_btb10" in thermals
        assert "lc08_btb11" not in thermals

    def test_aux_ubids(self, ref_registry):
        aux = ref_registry.aux_ubids()
        assert set(aux) == set(AUX_UBIDS)
        for name, expect in AUX_UBIDS.items():
            assert _lower(aux[name]) == _lower(expect), name

    def test_partial_registry_keeps_builtin_tables_for_missing_half(self):
        """Split ARD/AUX services: an AUX-only registry must derive the AUX
        half and keep the built-in ARD tables (and vice versa), not crash."""
        aux_only = Registry(
            [e for e in _mini_registry_entries(100)
             if e["ubid"].startswith("AUX_")])
        ard, aux, dtypes, sensor = ChipmunkSource._derive(aux_only)
        assert ard is ARD_UBIDS
        assert aux["dem"] == ("AUX_DEM",)
        assert dtypes["lt04_srb1"] == np.int16      # fallback half
        assert dtypes["AUX_DEM"] == np.float32      # registry half
        ard_only = Registry(
            [e for e in _mini_registry_entries(100)
             if not e["ubid"].startswith("AUX_")])
        ard2, aux2, _, _ = ChipmunkSource._derive(ard_only)
        assert aux2 is AUX_UBIDS
        assert ard2["blues"] == ("XX01_SRB1",)

    def test_partial_registry_with_foreign_geometry_is_rejected(self):
        """A one-half registry at side!=100 cannot be mixed with the
        100x100 built-in tables covering its other half."""
        aux_only_300 = Registry(
            [e for e in _mini_registry_entries(300)
             if e["ubid"].startswith("AUX_")])
        with pytest.raises(LookupError, match="partial registry"):
            ChipmunkSource._derive(aux_only_300)

    def test_wire_dtypes(self, ref_registry):
        r = ref_registry
        assert r.wire_dtype("LC08_SRB2") == np.int16
        assert r.wire_dtype("LC08_PIXELQA") == np.uint16
        assert r.wire_dtype("AUX_DEM") == np.float32
        assert r.wire_dtype("AUX_ASPECT") == np.int16
        assert r.wire_dtype("AUX_MPW") == np.uint8    # BYTE
        assert r.wire_dtype("AUX_TRENDS") == np.uint8

    def test_chip_side(self, ref_registry):
        used = [u for us in ref_registry.ard_ubids().values() for u in us]
        assert ref_registry.chip_side(used) == 100
        assert ref_registry.chip_side() == 100  # uniform across all 97


# ---------------------------------------------------------------------------
# Synthetic registries: a hypothetical new sensor configures itself
# ---------------------------------------------------------------------------

def _entry(ubid, data_type, tags, shape=(50, 50)):
    return {"ubid": ubid, "data_type": data_type, "tags": list(tags),
            "data_shape": list(shape)}


def _mini_registry_entries(side=50):
    colors = ["blue", "green", "red", "nir", "swir1", "swir2"]
    ents = [_entry(f"XX01_SRB{i+1}", "INT16", ["sr", c, "xx01"],
                   (side, side)) for i, c in enumerate(colors)]
    ents.append(_entry("XX01_BTB6", "INT16", ["bt", "xx01"], (side, side)))
    ents.append(_entry("XX01_PIXELQA", "UINT16", ["pixelqa", "qa", "xx01"],
                       (side, side)))
    aux_types = {"dem": "FLOAT32", "trends": "BYTE", "aspect": "INT16",
                 "posidex": "FLOAT32", "slope": "FLOAT32", "mpw": "BYTE"}
    for name, dt in aux_types.items():
        ents.append(_entry(f"AUX_{name.upper()}", dt, ["aux", name],
                           (side, side)))
    return ents


def test_new_sensor_is_configuration_not_code():
    reg = Registry(_mini_registry_entries())
    ard = reg.ard_ubids()
    assert ard["blues"] == ("XX01_SRB1",)
    assert ard["thermals"] == ("XX01_BTB6",)
    assert ard["qas"] == ("XX01_PIXELQA",)
    assert reg.aux_ubids()["mpw"] == ("AUX_MPW",)
    assert reg.chip_side() == 50


def test_chipmunk_source_uses_registry_geometry_and_dtypes():
    """End-to-end: /registry + /chips served by a fake; the source must
    decode with registry dtypes and the registry chip side (50, not 100)."""
    side = 50
    entries = _mini_registry_entries(side)
    dtypes = {e["ubid"]: {"INT16": np.int16, "UINT16": np.uint16,
                          "BYTE": np.uint8, "FLOAT32": np.float32
                          }[e["data_type"]] for e in entries}

    def fake_get(url):
        if url.endswith("/registry"):
            return entries
        q = parse_qs(urlparse(url).query)
        ubid = q["ubid"][0]
        a = np.full((side, side), 7, dtypes[ubid])
        return [{"x": -100, "y": 100, "acquired": "1999-01-01T00:00:00Z",
                 "data": base64.b64encode(a.tobytes()).decode(),
                 "ubid": ubid}]

    src = ChipmunkSource("http://chipmunk/ard", http_get=fake_get)
    c = src.chip(-100, 100, "1998-01-01/2000-01-01")
    assert c.spectra.shape == (7, 1, side, side)
    assert np.all(c.spectra == 7)
    aux = src.aux(-100, 100)
    assert aux["dem"].dtype == np.float32
    assert aux["dem"].shape == (side, side)
    assert aux["mpw"].dtype == np.uint8


def test_fallback_to_builtin_tables_when_registry_unreachable():
    side = 100
    calls = []

    def fake_get(url):
        calls.append(url)
        if url.endswith("/registry"):
            raise OSError("registry down")
        q = parse_qs(urlparse(url).query)
        a = np.full((side, side), 3,
                    np.uint16 if "pixelqa" in q["ubid"][0] else np.int16)
        return [{"x": 0, "y": 0, "acquired": "1999-01-01T00:00:00Z",
                 "data": base64.b64encode(a.tobytes()).decode(),
                 "ubid": q["ubid"][0]}]

    src = ChipmunkSource("http://chipmunk/ard", http_get=fake_get)
    c = src.chip(0, 0, "1998-01-01/2000-01-01")
    assert c.spectra.shape == (7, 1, side, side)
    # registry probed exactly once, then the builtin tables took over
    assert sum(u.endswith("/registry") for u in calls) == 1


def test_pinned_registry_skips_fetch():
    side = 50
    entries = _mini_registry_entries(side)

    def fake_get(url):
        assert not url.endswith("/registry"), "pinned registry must not fetch"
        q = parse_qs(urlparse(url).query)
        a = np.zeros((side, side), np.uint16 if "PIXELQA" in q["ubid"][0]
                     else np.int16)
        return [{"x": 0, "y": 0, "acquired": "1999-01-01T00:00:00Z",
                 "data": base64.b64encode(a.tobytes()).decode(),
                 "ubid": q["ubid"][0]}]

    src = ChipmunkSource("http://chipmunk/ard", http_get=fake_get,
                         registry=Registry(entries))
    assert src.chip(0, 0, "1998-01-01/2000-01-01").spectra.shape[-1] == side


def test_chips_query_retries_lowercase_ubid():
    """The recorded /registry uses uppercase ubids while the recorded /chips
    interaction uses lowercase; an empty uppercase query must be retried
    lowercased so a case-sensitive service still yields data."""
    side = 50
    entries = _mini_registry_entries(side)
    served = []

    def fake_get(url):
        if url.endswith("/registry"):
            return entries
        q = parse_qs(urlparse(url).query)
        ubid = q["ubid"][0]
        served.append(ubid)
        if ubid != ubid.lower():
            return []   # case-sensitive service: only lowercase resolves
        a = np.zeros((side, side),
                     np.uint16 if "pixelqa" in ubid else np.int16)
        return [{"x": 0, "y": 0, "acquired": "1999-01-01T00:00:00Z",
                 "data": base64.b64encode(a.tobytes()).decode(),
                 "ubid": ubid}]

    src = ChipmunkSource("http://chipmunk/ard", http_get=fake_get)
    c = src.chip(0, 0, "1998-01-01/2000-01-01")
    assert c.dates.shape[0] == 1            # data arrived via the retry
    assert any(u == u.lower() for u in served)


def test_float_spectral_band_rejected_loudly():
    """A registry declaring float spectra violates the packed int16 wire
    contract; that must raise even under registry='auto' (falling back to
    builtin ubids against such a service would silently yield no data)."""
    from firebird_tpu.ingest.sources import UnsupportedWireError

    entries = _mini_registry_entries(100)
    for e in entries:
        if e["ubid"] == "XX01_SRB1":
            e["data_type"] = "FLOAT32"
    src = ChipmunkSource("http://chipmunk/ard",
                         http_get=lambda url: entries
                         if url.endswith("/registry") else [])
    with pytest.raises(UnsupportedWireError, match="blues"):
        src.chip(0, 0, "1998-01-01/2000-01-01")


def test_registry_error_paths():
    with pytest.raises(LookupError):
        Registry.fetch(lambda url: [], "http://x")
    reg = Registry([_entry("A_1", "INT16", ["sr", "blue"], (10, 10)),
                    _entry("B_1", "INT16", ["sr", "blue"], (20, 20))])
    with pytest.raises(ValueError):     # mixed chip sides
        reg.chip_side()
    with pytest.raises(LookupError):    # no thermal/qa tags at all
        reg.ard_ubids()
    bad = Registry([_entry("C_1", "COMPLEX64", ["sr", "blue"])])
    with pytest.raises(LookupError):
        bad.wire_dtype("C_1")
    with pytest.raises(LookupError):
        bad.wire_dtype("NOPE")
