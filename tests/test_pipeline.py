"""Zero-stall pipeline: bulk batch egress, device-side input staging,
buffer donation, and compile-warm startup (ISSUE 3).

The egress contract is the load-bearing one: ``drain_batch`` must issue
exactly ONE bulk device->host transfer per batch (``jax.device_get`` of
the whole batched ChipSegments), and the vectorized ``batch_frames``
must reproduce per-chip ``chip_frames`` bit-for-bit on a ragged, padded
final batch — both drivers drain through this one code path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firebird_tpu.ccd import format as ccdformat
from firebird_tpu.ccd import kernel
from firebird_tpu.config import Config
from firebird_tpu.driver import core
from firebird_tpu.ingest import SyntheticSource, pack
from firebird_tpu.ingest.packer import PackedChips
from firebird_tpu.obs import Counters
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.store import AsyncWriter, MemoryStore


@pytest.fixture(scope="module")
def ragged_batch():
    """3 real (pixel-sliced) chips padded to a 4-chip compiled shape —
    the ragged-final-batch case — plus the kernel result."""
    src = SyntheticSource(seed=3, start="1995-01-01", end="1997-01-01")
    p = pack([src.chip(100 + 3000 * i, 200) for i in range(3)], bucket=32)
    small = PackedChips(cids=p.cids, dates=p.dates,
                        spectra=p.spectra[:, :, :64, :],
                        qas=p.qas[:, :64, :], n_obs=p.n_obs)
    padded, n_real = core._pad_batch(small, 4)
    seg = kernel.detect_packed(padded, dtype=jnp.float64)
    return small, padded, n_real, seg


def _assert_col_equal(table, col, got, ref):
    assert len(got) == len(ref), (table, col)
    for a, b in zip(got, ref):
        if a is None or b is None:
            assert a is None and b is None, (table, col)
        elif isinstance(a, (list, np.ndarray)) \
                or isinstance(b, (list, np.ndarray)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"{table}.{col}")
        else:
            # NaN sentinel floats compare equal-as-NaN
            assert a == b or (a != a and b != b), (table, col, a, b)


def test_batch_frames_matches_chip_frames_on_ragged_padded_batch(
        ragged_batch):
    """The vectorized whole-batch formatter must equal the per-chip path
    on every column of every table, and drop the padded chips."""
    _, padded, n_real, seg = ragged_batch
    host = jax.device_get(seg)
    out = ccdformat.batch_frames(padded, host, n_real)
    assert len(out) == n_real                  # padded chips dropped
    for c, (cid, frames) in enumerate(out):
        assert cid == (int(padded.cids[c][0]), int(padded.cids[c][1]))
        ref = ccdformat.chip_frames(
            padded, c, kernel.chip_slice(seg, c, to_host=True))
        for table in ("chip", "pixel", "segment"):
            assert set(frames[table]) == set(ref[table])
            for col in ref[table]:
                _assert_col_equal(table, col, frames[table][col],
                                  ref[table][col])


def test_drain_batch_issues_one_bulk_device_get(ragged_batch, monkeypatch):
    """The egress regression contract: one ``jax.device_get`` per drained
    batch — never the old per-chip, per-field transfer pattern."""
    _, padded, n_real, seg = ragged_batch
    calls = {"n": 0}
    real_get = jax.device_get

    def counting_get(x):
        calls["n"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    store = MemoryStore("bulk")
    writer = AsyncWriter(store)
    counters = Counters()
    try:
        core.drain_batch(seg, padded, n_real, writer=writer,
                         counters=counters, dtype=jnp.float64)
        writer.flush()
    finally:
        writer.close()
    assert calls["n"] == 1
    # ... and the keyed per-chip writes all landed (resume invariant path)
    assert store.count("chip") == n_real
    assert store.count("pixel") == n_real * 64
    assert store.count("segment") >= n_real * 64
    assert counters.get("chips") == n_real
    assert counters.get("pixels") == n_real * 64


def test_drain_records_egress_metrics(ragged_batch):
    _, padded, n_real, seg = ragged_batch
    obs_metrics.reset_registry()
    store = MemoryStore("m")
    writer = AsyncWriter(store)
    try:
        core.drain_batch(seg, padded, n_real, writer=writer,
                         counters=Counters(), dtype=jnp.float64)
        writer.flush()
    finally:
        writer.close()
    snap = obs_metrics.get_registry().snapshot()
    assert snap["histograms"]["pipeline_d2h_seconds"]["count"] == 1
    assert snap["counters"]["wire_d2h_bytes"] > 0
    assert snap["counters"]["store_rows_written"] >= n_real * (1 + 64 + 64)
    obs_metrics.reset_registry()


def test_stage_batch_then_staged_dispatch_matches(ragged_batch):
    """The prefetch thread's product (StagedBatch) dispatches to the same
    result as the unstaged path, pads to the compiled shape, and records
    the staging histogram + H2D byte counter."""
    small, padded, n_real, seg = ragged_batch
    obs_metrics.reset_registry()
    staged = core.stage_batch(small, jnp.float64, "off", pad_to=4)
    assert staged.mesh is None
    assert staged.packed.n_chips == 4 and staged.n_real == 3
    seg2, r2 = core.detect_batch(small, jnp.float64, "off",
                                 staged=staged, donate=False)
    assert r2 == 3
    for f in ("n_segments", "seg_meta", "mask", "procedure"):
        np.testing.assert_array_equal(np.asarray(getattr(seg2, f))[:3],
                                      np.asarray(getattr(seg, f))[:3])
    snap = obs_metrics.get_registry().snapshot()
    assert snap["histograms"]["pipeline_stage_seconds"]["count"] == 1
    assert snap["counters"]["wire_h2d_bytes"] > 0
    obs_metrics.reset_registry()


def test_staged_sharded_dispatch_matches(ragged_batch):
    """Staging under the local device mesh: pads 3 -> 8 chips over the
    virtual devices and matches the single-device result."""
    small, _, _, seg = ragged_batch
    assert jax.local_device_count() == 8
    staged = core.stage_batch(small, jnp.float64, "auto")
    assert staged.mesh is not None and staged.packed.n_chips == 8
    seg2, r2 = core.detect_batch(small, jnp.float64, "auto", staged=staged)
    assert r2 == 3 and seg2.n_segments.shape[0] == 8
    np.testing.assert_array_equal(np.asarray(seg2.n_segments)[:3],
                                  np.asarray(seg.n_segments)[:3])


@pytest.mark.filterwarnings("ignore:Some donated buffers")
def test_donated_dispatch_matches_and_consumes_inputs(ragged_batch):
    """The donated jit twin computes the same result; donation is only
    honored on the single-dispatch (check_capacity=False) path."""
    small, _, _, seg = ragged_batch
    args = kernel.stage_packed(small, jnp.float64)
    out = kernel.detect_packed(small, dtype=jnp.float64,
                               check_capacity=False, staged=args,
                               donate=True)
    np.testing.assert_array_equal(np.asarray(out.n_segments),
                                  np.asarray(seg.n_segments)[:3])


@pytest.mark.slow  # ~46s (two full driver runs back-to-back); tier-1 (-m 'not slow') keeps the staging/egress pipeline rungs and `make pipeline-smoke` still proves the second-run compile-cache hit end-to-end
def test_warm_start_compile_cache_hit_on_second_run(tmp_path):
    """FIREBIRD_COMPILE_CACHE acceptance: run-1 warm compile populates
    the persistent cache (miss counted), and after dropping the in-memory
    jit cache a second warm compile of the same predicted shape HITS."""
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cfg = Config(store_backend="memory", source_backend="synthetic",
                 chips_per_batch=1, device_sharding="off",
                 compile_cache=str(tmp_path / "cache"))
    acq = "1995-01-01/1995-09-01"
    try:
        assert core.setup_compile_cache(cfg) == str(tmp_path / "cache")
        # Run 1 must trace from a clean slate: module-level lowering dedup
        # depends on the in-memory tracing caches, so a run 1 traced with
        # caches warmed by EARLIER tests (e.g. an x64 driver run) emits a
        # differently-numbered module — and writes a persistent-cache key
        # run 2's post-clear_caches canonical trace can never look up.
        jax.clear_caches()
        obs_metrics.reset_registry()
        t = core.warm_start(cfg, acq)
        assert t is not None
        t.join(timeout=600)
        assert not t.is_alive()
        snap = obs_metrics.get_registry().snapshot()
        assert snap["counters"]["warm_compiles"] == 1
        assert snap["histograms"]["warm_compile_seconds"]["count"] == 1
        assert os.listdir(cfg.compile_cache)       # entry written
        assert snap["counters"].get("compile_cache_misses", 0) > 0

        jax.clear_caches()
        obs_metrics.reset_registry()
        t2 = core.warm_start(cfg, acq)
        t2.join(timeout=600)
        assert not t2.is_alive()
        snap2 = obs_metrics.get_registry().snapshot()
        assert snap2["counters"].get("compile_cache_hits", 0) > 0
    finally:
        obs_metrics.reset_registry()
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          old_min)
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass


def test_warm_start_off_without_compile_cache():
    cfg = Config(store_backend="memory", compile_cache="")
    assert core.warm_start(cfg, "1995-01-01/1996-01-01") is None
    assert core.setup_compile_cache(cfg) is None


def test_predict_batch_shape_is_padded_and_bucketed():
    cfg = Config(chips_per_batch=3, device_sharding="off")
    C, T, wcap = core.predict_batch_shape(cfg, "1995-01-01/1996-06-01")
    assert C == 3
    assert T % cfg.obs_bucket == 0 and T >= 64
    assert wcap % 8 == 0 and wcap <= T
    # sharded: C rounds up to the device-count multiple
    C8, _, _ = core.predict_batch_shape(
        Config(chips_per_batch=3), "1995-01-01/1996-06-01")
    assert C8 == 8


def test_pipeline_depth_config():
    # default 3 since the wire diet: int-coded depth-sliced egress freed
    # the HBM one more in-flight batch pins (config.py rationale)
    assert Config().pipeline_depth == 3
    with pytest.raises(ValueError):
        Config(pipeline_depth=0)
    cfg = Config.from_env({"FIREBIRD_PIPELINE_DEPTH": "4",
                           "FIREBIRD_COMPILE_CACHE": "/tmp/cc"})
    assert cfg.pipeline_depth == 4 and cfg.compile_cache == "/tmp/cc"


def test_progress_reports_pipeline_occupancy():
    from firebird_tpu.obs import server as obs_server

    st = obs_server.RunStatus("r1", "changedetection", chips_total=4,
                              pipeline_depth=3)
    st.batch_dispatched()
    st.batch_dispatched()
    st.batch_done()
    prog = st.progress()
    kern = prog["pipeline"].pop("kernel")   # lane occupancy (test_compact)
    assert set(kern) == {"active_lane_rounds", "wasted_lane_rounds",
                         "wasted_share", "compactions"}
    assert prog["pipeline"] == {"depth": 3, "in_flight": 1,
                                "occupancy": round(1 / 3, 3)}
    assert obs_metrics.gauge("pipeline_inflight").value == 1
