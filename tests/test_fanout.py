"""Fanout plane: quadkey subscription index, sharded delivery, rollup.

Pins the contracts docs/ALERTS.md "Fanout plane" promises: index
audience == brute-force bbox scan (property test), per-(subscriber,
shard) cursors compose to exactly-once across deliverer incarnations,
delivery policies (immediate | digest | batch) shape POSTs without
bending the cursor rules, consecutive failures park a subscriber
instead of stalling its shard, and rollup is watermark + open-job
idempotent.  tools/fanout_loadtest.py proves the same at 1M-subscriber
scale; these are the fast seams.
"""

import json
import random
import sqlite3
import time
import urllib.error
import urllib.request

import pytest

from firebird_tpu.alerts import subindex
from firebird_tpu.alerts.fanout import FanoutDeliverer, rollup
from firebird_tpu.alerts.feed import AlertFeed, WebhookDeliverer
from firebird_tpu.alerts.log import AlertLog
from firebird_tpu.config import Config
from firebird_tpu.fleet.queue import FleetQueue
from firebird_tpu.serve import pyramid as pyr


def tile_pt(x, y):
    """A projection point inside base tile (x, y) — chips == base tiles,
    so records stamped here carry quadkey(Z_BASE, x, y)."""
    e = pyr.tile_extent(subindex.Z_BASE, x, y)
    return int(e["ulx"]) + 1, int(e["uly"]) - 1


def tile_mid(x, y):
    """The center of base tile (x, y) — inside even an inset AOI."""
    e = pyr.tile_extent(subindex.Z_BASE, x, y)
    return (e["ulx"] + e["lrx"]) / 2, (e["uly"] + e["lry"]) / 2


def rec_at(x, y, day, **kw):
    """An alert record inside base tile (x, y); unique per day."""
    px, py = tile_pt(x, y)
    r = {"cx": px, "cy": py, "px": px, "py": py, "break_day": float(day)}
    r.update(kw)
    return r


class Clock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


@pytest.fixture
def alog(tmp_path):
    al = AlertLog(str(tmp_path / "alerts.db"))
    yield al
    al.close()


def cfg_mem(**kw):
    return Config(store_backend="memory", fetch_retries=1, **kw)


# ---------------------------------------------------------------------------
# subindex: quadkey math
# ---------------------------------------------------------------------------

def test_zbase_and_shard_helpers():
    assert subindex.Z_BASE == pyr.Z_BASE
    assert subindex.shard_of("01230123012", 2) == "01"
    assert subindex.shard_of("01230123012", 0) == ""
    assert subindex.shard_prefixes("012") == ["", "0", "01"]
    assert subindex.shard_prefixes("") == []
    assert subindex.aoi_contains(None, 1.0, 1.0)
    assert subindex.aoi_contains((0, 0, 2, 2), 2.0, 0.0)
    assert not subindex.aoi_contains((0, 0, 2, 2), 3.0, 1.0)


def test_base_quadkey_and_point_cells():
    px, py = tile_pt(100, 200)
    qk = pyr.quadkey(subindex.Z_BASE, 100, 200)
    assert subindex.base_quadkey(px, py) == qk
    cells = subindex.point_cells(px, py)
    assert cells == [qk[:i] for i in range(subindex.Z_BASE + 1)]
    assert cells[0] == "" and len(cells) == subindex.Z_BASE + 1
    # off-domain chips cannot be indexed; points degrade to root-only
    assert subindex.base_quadkey(-1e9, 1e9) is None
    assert subindex.point_cells(-1e9, 1e9) == [""]


def test_cover_bbox_shapes():
    # a chip-interior AOI costs exactly its one base cell
    e = pyr.tile_extent(subindex.Z_BASE, 100, 200)
    bbox = (e["ulx"] + 10, e["lry"] + 10, e["lrx"] - 10, e["uly"] - 10)
    assert subindex.cover_bbox(bbox) == \
        [pyr.quadkey(subindex.Z_BASE, 100, 200)]
    # the whole domain is one root cell
    d = subindex._extent(0, 0, 0)
    assert subindex.cover_bbox(d) == [""]
    # slightly inset: the root splits, but the budget bounds the cost
    inset = (d[0] + 1, d[1] + 1, d[2] - 1, d[3] - 1)
    cells = subindex.cover_bbox(inset, max_cells=4)
    assert cells == sorted(pyr.quadkey(1, x, y)
                           for x in (0, 1) for y in (0, 1))
    cells = subindex.cover_bbox(inset, max_cells=64)
    assert 4 <= len(cells) <= 64
    # covering property: every in-bbox point has an ancestor cell
    got = set(cells)
    rng = random.Random(7)
    for _ in range(50):
        px = rng.uniform(inset[0], inset[2])
        py = rng.uniform(inset[1], inset[3])
        assert any(c in got for c in subindex.point_cells(px, py))
    # off-domain AOIs contain no indexable point
    assert subindex.cover_bbox((-1e9, 1e9, -1e9 + 5, 1e9 + 5)) == []
    with pytest.raises(ValueError):
        subindex.cover_bbox((5, 0, 0, 5))           # min > max
    with pytest.raises(ValueError):
        subindex.cover_bbox(bbox, max_cells=3)      # budget < one split


def test_property_index_matches_brute_force(alog):
    """The tentpole contract: audience through the quadkey cell index
    == a brute-force bbox scan, over randomized AOI sizes (100 m to
    ~2000 km half-widths) and random in-domain points."""
    rng = random.Random(20260807)
    dminx, dminy, dmaxx, dmaxy = subindex._extent(0, 0, 0)
    entries = []
    for i in range(100):
        cx = rng.uniform(dminx, dmaxx)
        cy = rng.uniform(dminy, dmaxy)
        half = 10.0 ** rng.uniform(2.0, 6.3)
        aoi = (cx - half, cy - half, cx + half, cy + half)
        assert len(subindex.cover_bbox(aoi)) <= subindex.MAX_CELLS
        entries.append({"url": f"http://s{i}/hook", "aoi": aoi})
    for i in range(10):
        entries.append({"url": f"http://g{i}/hook"})   # global
    ids = alog.subscribe_many(entries)
    assert len(ids) == 110
    for _ in range(120):
        px = rng.uniform(dminx, dmaxx)
        py = rng.uniform(dminy, dmaxy)
        assert alog.audience(px, py) == alog.audience_brute(px, py)


# ---------------------------------------------------------------------------
# AlertLog: migration, registration, shard queries
# ---------------------------------------------------------------------------

def test_migration_from_pre_fanout_schema(tmp_path):
    path = str(tmp_path / "old.db")
    con = sqlite3.connect(path)
    con.execute(
        "CREATE TABLE alerts ("
        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
        " cx INTEGER NOT NULL, cy INTEGER NOT NULL,"
        " px INTEGER NOT NULL, py INTEGER NOT NULL,"
        " break_day REAL NOT NULL, score REAL, magnitude REAL,"
        " run_id TEXT, detected_at TEXT,"
        " UNIQUE (px, py, break_day))")
    con.execute(
        "CREATE TABLE subscribers ("
        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
        " url TEXT NOT NULL UNIQUE,"
        " cursor INTEGER NOT NULL DEFAULT 0,"
        " created TEXT, last_ok TEXT,"
        " failures INTEGER NOT NULL DEFAULT 0)")
    con.execute("INSERT INTO subscribers (url, cursor) "
                "VALUES ('http://old/hook', 1)")
    con.execute("INSERT INTO alerts (cx, cy, px, py, break_day) "
                "VALUES (100, 200, 100, 200, 1000.0)")
    con.commit()
    con.close()
    al = AlertLog(path)
    try:
        sub = al.subscribers()[0]
        # legacy subscribers stay global, immediate, cursor intact
        assert sub["cursor"] == 1 and sub["aoi"] is None
        assert sub["mode"] == "immediate" and sub["parked_until"] is None
        px, py = tile_pt(100, 200)
        assert al.audience(px, py) == [sub["id"]]       # root-cell backfill
        # pre-migration rows carry no quadkey: rollup ignores them (the
        # flat deliverer still sweeps them)
        assert al.shards_since(0, 2) == []
        al.append([rec_at(100, 200, 2000)])
        shards = al.shards_since(0, 2)
        assert len(shards) == 1 and shards[0]["count"] == 1
        assert shards[0]["upto"] == 2
    finally:
        al.close()


def test_subscribe_validation_and_replace(alog):
    for bad in (dict(url="no-scheme"),
                dict(url="http://x/", mode="bogus"),
                dict(url="http://x/", mode="digest"),         # no window
                dict(url="http://x/", mode="batch")):         # no max_n
        with pytest.raises(ValueError):
            alog.subscribe(**bad)
    e = pyr.tile_extent(subindex.Z_BASE, 100, 200)
    aoi = (e["ulx"] + 10, e["lry"] + 10, e["lrx"] - 10, e["uly"] - 10)
    sid = alog.subscribe("http://x/hook", cursor=5, aoi=aoi)
    assert alog.audience(*tile_mid(100, 200)) == [sid]
    assert alog.audience(*tile_pt(500, 500)) == []
    # idempotent on url: cursor kept, AOI/policy REPLACED
    assert alog.subscribe("http://x/hook", mode="digest",
                          window_sec=60.0) == sid
    sub = alog.subscribers()[0]
    assert sub["cursor"] == 5 and sub["mode"] == "digest"
    assert sub["aoi"] is None and sub["window_sec"] == 60.0
    assert alog.audience(*tile_pt(500, 500)) == [sid]     # global now


def test_shard_queries_and_cursor_rules(alog):
    sid = alog.subscribe("http://s/hook")
    alog.append([rec_at(100, 200, 1000 + i) for i in range(3)])
    alog.append([rec_at(1500, 300, 2000)])     # different first digit
    shards = alog.shards_since(0, 2)
    assert [s["shard"] for s in shards] == ["00", "10"]
    assert [s["upto"] for s in shards] == [3, 4]
    page = alog.alerts_for_shard("00", upto=3)
    assert [a["id"] for a in page] == [1, 2, 3]
    assert all(a["qk"].startswith("00") for a in page)
    assert [a["id"] for a in alog.alerts_for_shard("10", upto=4)] == [4]
    # a global (root-cell) subscriber belongs to every shard
    for shard in ("00", "10"):
        assert [s["id"] for s in alog.shard_subscribers(shard)] == [sid]
    # forward-only per-shard cursors; sent_at survives cursor-only moves
    alog.advance_fanout(sid, "00", 10, sent_at=123.0)
    alog.advance_fanout(sid, "00", 5)
    assert alog.fanout_cursor(sid, "00") == 10
    assert alog.shard_subscribers("00")[0]["last_sent"] == 123.0
    alog.advance_fanout(sid, "00", 12)
    assert alog.fanout_cursor(sid, "00") == 12
    assert alog.fanout_cursor(sid, "10") == 0      # shards independent
    # the rollup watermark is forward-only too
    alog.set_rollup_cursor(4)
    alog.set_rollup_cursor(2)
    assert alog.rollup_cursor() == 4


def test_shard_drained_watermark_rules(alog):
    """Forward-only AND contiguous: a window may only extend the
    watermark if it starts at or below it — a newer window completing
    ahead of an in-flight older one must not mark it covered."""
    assert alog.shard_drained("00") == 0
    alog.set_shard_drained("00", 0, 5)       # contiguous from empty
    assert alog.shard_drained("00") == 5
    alog.set_shard_drained("00", 10, 20)     # gap: older window in flight
    assert alog.shard_drained("00") == 5
    alog.set_shard_drained("00", 3, 12)      # overlaps from below: extends
    assert alog.shard_drained("00") == 12
    alog.set_shard_drained("00", 12, 8)      # never rewinds
    assert alog.shard_drained("00") == 12
    alog.set_shard_drained("00", 12, 20)
    assert alog.shard_drained("00") == 20
    # a brand-new shard cannot bootstrap from a mid-log window either
    alog.set_shard_drained("zz", 4, 9)
    assert alog.shard_drained("zz") == 0


def test_status_fanout_block(alog):
    alog.subscribe("http://a/hook")
    alog.subscribe("http://b/hook", mode="batch", max_n=2)
    s = alog.status()["fanout"]
    assert s["cells"] == 2
    assert s["by_mode"] == {"immediate": 1, "batch": 1}
    assert s["parked"] == 0 and s["rollup_cursor"] == 0


# ---------------------------------------------------------------------------
# FanoutDeliverer: exactly-once, policies, parking
# ---------------------------------------------------------------------------

def test_exactly_once_across_incarnations(alog):
    """A deliverer dying mid-shard (SIGKILL-shaped: cursor durable,
    process gone) hands a successor exactly the undelivered remainder —
    the sharded analog of the flat catch-up test."""
    sid = alog.subscribe("http://sink/hook")
    alog.append([rec_at(100, 200, 1000 + i) for i in range(10)])
    shard = alog.shards_since(0, 2)[0]["shard"]
    got, calls = [], {"n": 0}

    def post_then_die(url, body, timeout):
        calls["n"] += 1
        if calls["n"] > 1:
            raise OSError("killed")
        got.append(json.loads(body))
        return 200

    d1 = FanoutDeliverer(alog, cfg_mem(), post=post_then_die,
                         sleep=lambda s: None)
    assert d1.drain_shard(shard, 10, batch=4) == 4    # partial, "dies"
    assert alog.fanout_cursor(sid, shard) == 4        # durable
    d2 = FanoutDeliverer(
        alog, cfg_mem(), sleep=lambda s: None,
        post=lambda u, b, t: got.append(json.loads(b)) or 200)
    assert d2.drain_shard(shard, 10, batch=4) == 6    # remainder only
    ids = [a["id"] for doc in got for a in doc["alerts"]]
    assert ids == list(range(1, 11))                  # exactly once
    # clean completion RETIRES the catch-up row (reads as cursor 0);
    # the shard's drained watermark is what marks the window covered
    assert alog.fanout_cursor(sid, shard) == 0
    assert alog.subscribers()[0]["failures"] == 0     # 2xx healed
    # a duplicate job over the drained window is a no-op
    n = len(got)
    assert d2.drain_shard(shard, 10, batch=4) == 0 and len(got) == n


def test_aoi_filtered_subscriber_pays_nothing(alog):
    # far: AOI in tile (101, 201) — same "00" shard but a different
    # cell, so the audience probe never even visits it.  near: AOI in
    # the ALERT tile (100, 200) but inset past the corner the records
    # land on — visited as a candidate, filtered by bbox, no POST.
    e = pyr.tile_extent(subindex.Z_BASE, 101, 201)
    far = alog.subscribe("http://far/hook", aoi=(
        e["ulx"] + 10, e["lry"] + 10, e["lrx"] - 10, e["uly"] - 10))
    e = pyr.tile_extent(subindex.Z_BASE, 100, 200)
    near = alog.subscribe("http://near/hook", aoi=(
        e["ulx"] + 10, e["lry"] + 10, e["lrx"] - 10, e["uly"] - 10))
    alog.append([rec_at(100, 200, 1000 + i) for i in range(3)])
    posts = []
    d = FanoutDeliverer(alog, cfg_mem(), sleep=lambda s: None,
                        post=lambda u, b, t: posts.append(u) or 200)
    assert d.drain_shard("00", 3) == 0
    assert posts == []                                 # nothing POSTed
    # neither holds a catch-up row: no row == caught up through the
    # shard's drained watermark, and no per-subscriber write happened
    assert alog.fanout_cursor(far, "00") == 0
    assert alog.fanout_cursor(near, "00") == 0
    # a later record inside near's AOI delivers ONLY the new record
    px, py = tile_mid(100, 200)
    alog.append([{"cx": px, "cy": py, "px": px, "py": py,
                  "break_day": 5000.0}])
    assert d.drain_shard("00", 4, since=3) == 1
    assert posts == ["http://near/hook"]


def test_batch_mode_chunks_posts(alog):
    sid = alog.subscribe("http://b/hook", mode="batch", max_n=3)
    alog.append([rec_at(100, 200, 1000 + i) for i in range(8)])
    got = []
    d = FanoutDeliverer(alog, cfg_mem(), sleep=lambda s: None,
                        post=lambda u, b, t: got.append(json.loads(b))
                        or 200)
    assert d.drain_shard("00", 8) == 8
    assert [doc["count"] for doc in got] == [3, 3, 2]
    assert all(doc["schema"] == "firebird-alert-webhook/1" for doc in got)
    # intermediate cursors are real ids; the final one is the job bound
    assert [doc["cursor"] for doc in got] == [3, 6, 8]
    assert alog.fanout_cursor(sid, "00") == 0          # row retired


def test_digest_holds_window_then_flushes(alog):
    clk = Clock(1000.0)
    sid = alog.subscribe("http://d/hook", mode="digest", window_sec=100.0)
    alog.append([rec_at(100, 200, 1000 + i) for i in range(3)])
    got = []
    d = FanoutDeliverer(alog, cfg_mem(), clock=clk, sleep=lambda s: None,
                        post=lambda u, b, t: got.append(json.loads(b))
                        or 200)
    assert d.drain_shard("00", 3) == 3                 # first: no window yet
    assert len(got) == 1 and got[0]["schema"] == "firebird-alert-digest/1"
    assert got[0]["count"] == 3
    alog.append([rec_at(100, 200, 2000 + i) for i in range(2)])
    clk.t = 1050.0
    assert d.drain_shard("00", 5) == 0                 # window open: held
    assert len(got) == 1 and alog.fanout_cursor(sid, "00") == 3
    clk.t = 1200.0
    assert d.drain_shard("00", 5) == 2                 # window lapsed
    assert got[-1]["count"] == 2
    assert [a["id"] for a in got[-1]["alerts"]] == [4, 5]
    assert alog.fanout_cursor(sid, "00") == 5


def test_digest_row_survives_unmatched_window(alog):
    """A digest subscriber's cursor row is its window clock: windows
    whose alerts miss its AOI catch the row up CURSOR-ONLY (never
    retire it), so last_sent keeps gating the next flush."""
    clk = Clock(1000.0)
    e = pyr.tile_extent(subindex.Z_BASE, 100, 200)
    sid = alog.subscribe(
        "http://d/hook", mode="digest", window_sec=100.0,
        aoi=(e["ulx"] + 10, e["lry"] + 10, e["lrx"] - 10, e["uly"] - 10))
    px, py = tile_mid(100, 200)
    alog.append([{"cx": px, "cy": py, "px": px, "py": py,
                  "break_day": 1000.0}])
    got = []
    d = FanoutDeliverer(alog, cfg_mem(), clock=clk, sleep=lambda s: None,
                        post=lambda u, b, t: got.append(json.loads(b))
                        or 200)
    assert d.drain_shard("00", 1) == 1        # flushes; row persists
    assert alog.fanout_cursor(sid, "00") == 1
    # a window whose alert lands at the tile corner, outside the inset
    # AOI: visited, no hit, cursor catches up, row (last_sent) survives
    alog.append([rec_at(100, 200, 3000)])
    clk.t = 1050.0
    assert d.drain_shard("00", 2, since=1) == 0
    assert alog.fanout_cursor(sid, "00") == 2
    # matching alert inside the still-open window: held on last_sent
    alog.append([{"cx": px, "cy": py, "px": px + 1, "py": py - 1,
                  "break_day": 4000.0}])
    assert d.drain_shard("00", 3, since=2) == 0
    assert len(got) == 1
    clk.t = 1200.0                            # window lapsed: flushes
    assert d.drain_shard("00", 3, since=2) == 1
    assert [a["id"] for a in got[-1]["alerts"]] == [3]


def test_parking_backoff_and_heal(alog):
    cfg = cfg_mem(fanout_park_after=2, fanout_park_base_sec=1.0,
                  fanout_park_cap_sec=2.0)
    alog.subscribe("http://dead/hook")
    alog.append([rec_at(100, 200, 1500)])
    calls = []

    def post(url, body, timeout):
        calls.append(url)
        raise OSError("connection refused")

    clk = Clock(1000.0)
    d = FanoutDeliverer(alog, cfg, post=post, sleep=lambda s: None,
                        clock=clk, rng=random.Random(0))
    assert d.drain_shard("00", 1) == 0
    sub = alog.subscribers()[0]
    assert sub["failures"] == 1 and sub["parked_until"] is None
    assert d.drain_shard("00", 1) == 0      # 2nd consecutive: parked
    sub = alog.subscribers()[0]
    assert sub["failures"] == 2
    assert 1001.0 <= sub["parked_until"] <= 1002.0   # base..cap past clock
    n = len(calls)
    assert d.drain_shard("00", 1) == 0      # parked: not even attempted
    assert len(calls) == n
    clk.t = 1010.0                          # backoff elapsed; endpoint up
    d._post = lambda u, b, t: 200
    assert d.drain_shard("00", 1) == 1
    sub = alog.subscribers()[0]
    assert sub["failures"] == 0 and sub["parked_until"] is None


def test_flat_deliverer_parks_dead_subscriber(alog):
    """The head-of-line regression: one dead webhook must cost the
    sweep a row check, not its retry budget every tick — the live
    subscriber behind it delivers on the same sweep."""
    cfg = cfg_mem(fanout_park_after=1)
    alog.append([rec_at(100, 200, 1000 + i) for i in range(3)])
    alog.subscribe("http://dead/hook")
    alog.subscribe("http://live/hook")
    calls = []

    def post(url, body, timeout):
        calls.append(url)
        if "dead" in url:
            raise OSError("connection refused")
        return 200

    d = WebhookDeliverer(alog, cfg, post=post, sleep=lambda s: None)
    assert d.deliver_once() == 3            # live delivered despite dead
    subs = {s["url"]: s for s in alog.subscribers()}
    assert subs["http://live/hook"]["cursor"] == 3
    assert subs["http://dead/hook"]["cursor"] == 0
    assert subs["http://dead/hook"]["parked_until"] is not None
    n_dead = calls.count("http://dead/hook")
    assert d.deliver_once() == 0            # parked: dead skipped outright
    assert calls.count("http://dead/hook") == n_dead


# ---------------------------------------------------------------------------
# Rollup + fleet integration
# ---------------------------------------------------------------------------

def test_rollup_watermark_and_open_job_skip(tmp_path, alog):
    from firebird_tpu.fleet import plan

    cfg = cfg_mem()
    queue = FleetQueue(str(tmp_path / "fleet.db"), lease_sec=300.0)
    try:
        alog.subscribe("http://s/hook")
        alog.append([rec_at(100, 200, 1000 + i) for i in range(3)])
        alog.append([rec_at(1500, 300, 2000)])
        ids = rollup(alog, queue, cfg)
        assert len(ids) == 2
        upto = {p["shard"]: p["upto"]
                for _, p in queue.open_payloads("fanout")}
        assert upto == {"00": 3, "10": 4}
        assert alog.rollup_cursor() == 4
        assert rollup(alog, queue, cfg) == []          # watermark holds
        # a new alert re-rolls ONLY its shard, past the open job's bound
        alog.append([rec_at(100, 200, 3000)])
        ids2 = rollup(alog, queue, cfg)
        assert len(ids2) == 1
        assert queue.job(ids2[0])["payload"]["shard"] == "00"
        assert queue.job(ids2[0])["payload"]["upto"] == 5
        # re-reporting shards an open job already covers is a no-op
        assert plan.enqueue_fanout(
            queue, [{"shard": s, "upto": u, "count": 1}
                    for s, u in upto.items()]) == []
    finally:
        queue.close()


def test_worker_runs_fanout_job(tmp_path, monkeypatch):
    from firebird_tpu.alerts import fanout as fanoutlib
    from firebird_tpu.fleet.worker import FleetWorker

    cfg = cfg_mem(alert_db=str(tmp_path / "alerts.db"))
    al = AlertLog(cfg.alert_db)
    queue = FleetQueue(str(tmp_path / "fleet.db"), lease_sec=300.0)
    try:
        sid = al.subscribe("http://sink/hook")
        al.append([rec_at(100, 200, 1000 + i) for i in range(5)])
        assert len(rollup(al, queue, cfg)) == 1
        got = []
        monkeypatch.setattr(
            fanoutlib, "_default_post",
            lambda url, body, timeout: got.append(json.loads(body)) or 200)
        w = FleetWorker(cfg, queue, worker_id="t:1", sleep=lambda s: None)
        summary = w.run(until_drained=True)
        assert summary["acked"] == 1 and summary["queue"]["done"] == 1
        assert sum(doc["count"] for doc in got) == 5
        # retired on clean completion; the watermark covers the window
        assert al.fanout_cursor(sid, got[0]["shard"]) == 0
        assert al.shard_drained(got[0]["shard"]) == 5
    finally:
        queue.close()
        al.close()


# ---------------------------------------------------------------------------
# Serve endpoint + SLO + knobs
# ---------------------------------------------------------------------------

@pytest.fixture
def served(tmp_path):
    from firebird_tpu.serve import api as serve_api
    from firebird_tpu.store import open_store

    cfg = Config(store_backend="memory", serve_deadline_sec=5.0)
    store = open_store("memory", "", cfg.keyspace())
    alog = AlertLog(str(tmp_path / "alerts.db"))
    service = serve_api.ServeService(store, cfg,
                                     alerts=AlertFeed(alog, cfg))
    srv = serve_api.start_serve_server(0, service, host="127.0.0.1")
    yield f"http://127.0.0.1:{srv.port}", alog
    srv.close()
    alog.close()
    store.close()


def _post(url):
    r = urllib.request.urlopen(
        urllib.request.Request(url, method="POST"), timeout=10)
    return r.status, json.loads(r.read())


def test_webhook_registration_with_aoi_and_policy(served):
    base, alog = served
    e = pyr.tile_extent(subindex.Z_BASE, 100, 200)
    bbox = f"{e['ulx'] + 10},{e['lry'] + 10},{e['lrx'] - 10},{e['uly'] - 10}"
    code, doc = _post(base + "/v1/alerts/webhooks?url=http://h/hook"
                      f"&bbox={bbox}&mode=batch&max_n=5")
    assert code == 200 and doc["mode"] == "batch"
    assert len(doc["aoi"]) == 4
    sub = alog.subscribers()[0]
    assert sub["mode"] == "batch" and sub["max_n"] == 5
    assert sub["aoi"] is not None
    assert alog.audience(*tile_mid(100, 200)) == [sub["id"]]
    assert alog.audience(*tile_pt(500, 500)) == []
    # policy errors are a 400, not a 500
    for bad in ("?url=http://h2/hook&mode=bogus",
                "?url=http://h2/hook&mode=digest"):
        try:
            _post(base + "/v1/alerts/webhooks" + bad)
            assert False, f"expected 400 for {bad}"
        except urllib.error.HTTPError as err:
            assert err.code == 400


def test_fanout_slo_objective_in_default_budget():
    from firebird_tpu.obs import slo

    kind, metric, stat, _ = slo.OBJECTIVES["fanout_p99"]
    assert (kind, metric, stat) == \
        ("histogram", "fanout_completion_seconds", "p99")
    budgets = {b["name"]: b
               for b in slo.parse_budget_spec(slo.DEFAULT_BUDGET_SPEC)}
    assert budgets["fanout_p99"]["threshold"] == 30.0
    assert budgets["fanout_p99"]["window_sec"] == 7 * 86400.0


def test_fanout_knobs_validate_and_parse():
    for bad in (dict(fanout_shard_prefix=0),
                dict(fanout_shard_prefix=12),
                dict(fanout_max_cells=3),
                dict(fanout_park_after=0),
                dict(fanout_park_base_sec=2.0, fanout_park_cap_sec=1.0),
                dict(fanout_poll_sec=0.0)):
        with pytest.raises(ValueError):
            Config(store_backend="memory", **bad)
    cfg = Config.from_env({"FIREBIRD_FANOUT": "0",
                           "FIREBIRD_FANOUT_SHARD_PREFIX": "3",
                           "FIREBIRD_FANOUT_PARK_AFTER": "5"})
    assert cfg.fanout_enabled is False
    assert cfg.fanout_shard_prefix == 3 and cfg.fanout_park_after == 5
