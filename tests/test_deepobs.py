"""Deep observability: device profiling, cross-thread trace propagation,
SLO tracking, and the crash flight recorder (obs/profiling.py, obs/slo.py,
obs/flightrec.py + the TraceContext plumbing in obs/tracing.py,
obs/metrics.py exemplars, obs/jsonlog.py, and the drivers)."""

import glob
import gzip
import json
import logging
import os
import threading
import time

import pytest

from firebird_tpu.config import Config
from firebird_tpu.obs import flightrec, jsonlog, profiling
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import server as obs_server
from firebird_tpu.obs import slo as slomod
from firebird_tpu.obs import tracing
from firebird_tpu.obs.watchdog import Watchdog


@pytest.fixture
def fresh_metrics():
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


@pytest.fixture
def disarmed():
    """Every flight-recorder test leaves the process hooks restored."""
    yield
    flightrec.disarm()


# ---------------------------------------------------------------------------
# TraceContext: thread-local activation, ids, exemplars
# ---------------------------------------------------------------------------

def test_trace_context_activation_is_thread_local():
    assert tracing.current_context() is None
    ctx = tracing.TraceContext("run-x/b0", run_id="run-x")
    seen = {}

    def other():
        seen["other"] = tracing.current_context()

    with tracing.activate(ctx):
        assert tracing.current_context() is ctx
        t = threading.Thread(target=other)
        t.start()
        t.join()
        inner = tracing.TraceContext("run-x/b1")
        with tracing.activate(inner):
            assert tracing.current_context() is inner
        assert tracing.current_context() is ctx
    assert tracing.current_context() is None
    assert seen["other"] is None          # contexts never leak across threads
    # activate(None) is a no-op so call sites thread optional contexts
    with tracing.activate(None):
        assert tracing.current_context() is None


def test_new_batch_ids_are_unique_and_run_scoped():
    a = tracing.new_batch_id("rid")
    b = tracing.new_batch_id("rid")
    assert a != b and a.startswith("rid/b") and b.startswith("rid/b")
    assert tracing.new_batch_id(None).startswith("run/b")


def test_exemplar_payload_carries_batch_and_last_span_id():
    assert tracing.exemplar() is None     # outside any unit of work
    tracing.start(run_id="rid")           # span ids mint only when spans
    try:                                  # actually record
        with tracing.activate(tracing.TraceContext("rid/b7")):
            with tracing.span("fetch"):
                pass
            ex = tracing.exemplar()
            assert ex["batch"] == "rid/b7" and ex["span_id"] > 0
    finally:
        tracing.stop()


def test_span_records_batch_and_span_id_in_args(tmp_path):
    tr = tracing.start(run_id="rid")
    try:
        with tracing.activate(tracing.TraceContext("rid/b0", run_id="rid")):
            with tracing.span("fetch", chips=2):
                pass
        with tracing.span("pack"):        # outside any context
            pass
    finally:
        tracing.stop()
    events = [e for e in tr.to_chrome_trace()["traceEvents"]
              if e.get("ph") == "X"]
    fetch = next(e for e in events if e["name"] == "fetch")
    assert fetch["args"]["batch"] == "rid/b0"
    assert fetch["args"]["span_id"] > 0
    pack = next(e for e in events if e["name"] == "pack")
    assert "batch" not in pack["args"] and pack["args"]["span_id"] > 0


# ---------------------------------------------------------------------------
# Histogram exemplars
# ---------------------------------------------------------------------------

def test_histogram_keeps_slowest_exemplars(fresh_metrics):
    h = obs_metrics.histogram("x_seconds")
    for i in range(6):
        with tracing.activate(tracing.TraceContext(f"r/b{i}")):
            h.observe(float(i))
    h.observe(99.0)                       # no context: no exemplar
    snap = h.snapshot()
    ex = snap["exemplars"]
    assert len(ex) == obs_metrics.EXEMPLAR_SLOTS
    assert [e["value"] for e in ex] == sorted(
        (e["value"] for e in ex), reverse=True)
    assert ex[0]["batch"] == "r/b5"       # the slowest traced observation
    assert all("batch" in e for e in ex)


def test_exemplars_survive_fleet_merge(fresh_metrics):
    a = obs_metrics.Histogram("m_seconds")
    b = obs_metrics.Histogram("m_seconds")
    with tracing.activate(tracing.TraceContext("hostA/b0")):
        a.observe(1.0)
    with tracing.activate(tracing.TraceContext("hostB/b0")):
        b.observe(5.0)
    merged = obs_metrics.merge_histogram_snapshots(
        [a.snapshot(), b.snapshot()])
    assert merged["count"] == 2
    assert merged["exemplars"][0]["batch"] == "hostB/b0"   # fleet slowest


def test_jsonlog_line_carries_batch_inside_context():
    fmt = jsonlog.JsonFormatter()
    rec = logging.LogRecord("firebird.x", logging.INFO, __file__, 1,
                            "hello", (), None)
    with tracing.activate(tracing.TraceContext("rid/b3", run_id="rid")):
        doc = json.loads(fmt.format(rec))
    assert doc["batch"] == "rid/b3"
    doc = json.loads(fmt.format(rec))     # outside: no batch key
    assert "batch" not in doc


# ---------------------------------------------------------------------------
# SLO tracking
# ---------------------------------------------------------------------------

def test_slo_spec_grammar():
    assert slomod.parse_spec("batch_p95=30;serve_p99=2") == \
        [("batch_p95", 30.0), ("serve_p99", 2.0)]
    assert slomod.parse_spec("") == []
    with pytest.raises(ValueError, match="unknown SLO objective"):
        slomod.parse_spec("bogus=1")
    with pytest.raises(ValueError, match="not name=target"):
        slomod.parse_spec("batch_p95")
    with pytest.raises(ValueError, match="not a number"):
        slomod.parse_spec("batch_p95=fast")
    with pytest.raises(ValueError, match="must be > 0"):
        slomod.parse_spec("batch_p95=0")


def test_slo_config_fail_fast():
    Config(slo="batch_p95=10")            # valid
    Config(slo="0")                       # disabled is valid
    with pytest.raises(ValueError):
        Config(slo="nope=1")


def test_slo_evaluation_pass_fail_and_no_data():
    metrics = {"histograms": {
        "pipeline_drain_seconds": {"count": 10, "p95": 12.0},
    }}
    out = slomod.evaluate_snapshot(metrics, spec="batch_p95=30;serve_p99=2")
    assert out["ok"] is True and out["violations"] == 0
    by = {o["name"]: o for o in out["objectives"]}
    assert by["batch_p95"]["ok"] is True
    assert by["batch_p95"]["value_sec"] == 12.0
    # serve never served: neither pass nor fail
    assert by["serve_p99"]["ok"] is None

    out = slomod.evaluate_snapshot(metrics, spec="batch_p95=10")
    assert out["ok"] is False and out["violations"] == 1


def test_slo_violation_carries_exemplars_and_freshness_reads_watchdog():
    metrics = {"histograms": {"pipeline_drain_seconds": {
        "count": 3, "p95": 50.0,
        "exemplars": [{"value": 55.0, "batch": "r/b9", "span_id": 4}]}}}
    out = slomod.evaluate_snapshot(metrics, watchdog={
        "last_beat_age_sec": 700.0}, spec="batch_p95=30;freshness=600")
    by = {o["name"]: o for o in out["objectives"]}
    assert by["batch_p95"]["ok"] is False
    assert by["batch_p95"]["exemplars"][0]["batch"] == "r/b9"
    assert by["freshness"]["ok"] is False
    assert out["violations"] == 2
    # "0" disables wholesale
    assert slomod.evaluate_snapshot(metrics, spec="0")["objectives"] == []


def test_slo_endpoint_and_report_block(fresh_metrics):
    """/slo serves the evaluation against the LIVE registry and
    build_report always carries the slo block."""
    from firebird_tpu.obs import report as obs_report

    obs_metrics.histogram("pipeline_drain_seconds").observe(1.0)
    status = obs_server.set_status(obs_server.RunStatus(
        "r", "test", slo_spec="batch_p95=30"))
    try:
        srv = obs_server.start_ops_server(0, status, host="127.0.0.1")
        try:
            import urllib.request
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/slo", timeout=5)
            doc = json.loads(r.read())
            assert doc["spec"] == "batch_p95=30" and doc["ok"] is True
            assert doc["objectives"][0]["value_sec"] == 1.0
        finally:
            srv.close()
        rep = obs_report.build_report(run={"run_id": "r"})
        assert rep["slo"]["spec"] == "batch_p95=30"
        assert rep["profile"]["device_time"]["source"] == "none"
    finally:
        obs_server.clear_status()


def test_slo_reevaluated_over_merged_fleet_reports(fresh_metrics):
    """Per-host verdicts cannot be combined — the merge re-evaluates over
    the merged histograms (a fleet p95 is not any host's p95)."""
    from firebird_tpu.obs import report as obs_report

    def host_report(v):
        obs_metrics.reset_registry()
        h = obs_metrics.histogram("pipeline_drain_seconds")
        for _ in range(50):
            h.observe(v)
        rep = obs_report.build_report(run={"run_id": "r"})
        return json.loads(json.dumps(rep))

    fast, slow = host_report(1.0), host_report(40.0)
    assert fast["slo"]["ok"] is True
    merged = obs_report.merge_reports([fast, slow])
    by = {o["name"]: o for o in merged["slo"]["objectives"]}
    assert by["batch_p95"]["ok"] is False     # the fleet p95 is the slow half
    assert merged["profile"]["device_time"]["source"] == "none"


# ---------------------------------------------------------------------------
# Device profiling
# ---------------------------------------------------------------------------

def _write_trace(dirpath, events):
    os.makedirs(dirpath, exist_ok=True)
    with gzip.open(os.path.join(dirpath, "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_attribution_buckets_by_kernel_name(tmp_path):
    _write_trace(str(tmp_path / "plugins" / "profile" / "x"), [
        {"ph": "X", "name": "fused_lasso_cd_kernel", "dur": 2000.0},
        {"ph": "X", "name": "monitor_chain_scored", "dur": 1000.0},
        {"ph": "X", "name": "compact_scatter_prefix", "dur": 500.0},
        {"ph": "X", "name": "mystery_op", "dur": 250.0},
        {"ph": "B", "name": "not_complete", "dur": 9e9},   # skipped
    ])
    a = profiling.attribute_phases(str(tmp_path))
    assert a["source"] == "trace" and a["events"] == 4
    assert a["fit_ms"] == 2.0 and a["monitor_ms"] == 1.0
    assert a["compaction_ms"] == 0.5 and a["other_ms"] == 0.25
    assert a["total_ms"] == 3.75


def test_attribution_zero_structure_when_no_trace(tmp_path):
    a = profiling.attribute_phases(str(tmp_path))
    assert a["source"] == "no-trace-files" and a["total_ms"] == 0.0
    assert set(f"{p}_ms" for p in profiling.PHASES) < set(a)


def test_profiler_window_real_capture(tmp_path, fresh_metrics):
    """A real (tiny) jax.profiler window on the CPU backend: artifact
    files land under window_00/ and the summary carries attribution —
    the POST /profile acceptance path minus HTTP."""
    import jax.numpy as jnp

    prof = profiling.DeviceProfiler(str(tmp_path / "device_profile"))
    x = jnp.ones((64, 64))
    (x @ x).block_until_ready()
    info = prof.window(0.05, block=True)
    assert "error" not in info, info
    assert info["trace_files"] >= 1
    assert glob.glob(os.path.join(info["dir"], "**", "*.trace.json.gz"),
                     recursive=True)
    s = prof.summary()
    assert len(s["windows"]) == 1 and not s["in_flight"]
    assert set(f"{p}_ms" for p in profiling.PHASES) < set(s["device_time"])
    assert obs_metrics.counter("profile_windows").value == 1


def test_profiler_single_window_at_a_time_and_early_close(tmp_path):
    prof = profiling.DeviceProfiler(str(tmp_path / "dp"))
    prof.window(60.0)                     # async; would run a minute
    with pytest.raises(profiling.ProfilerBusy):
        prof.window(1.0)
    # Generous join bound: on a contended host the capture thread's
    # start/stop_trace can take tens of seconds to get scheduled, and a
    # timed-out join here reads as a lost window (observed flake under
    # full-suite load).  The join returns the moment the thread ends,
    # so the typical cost is unchanged.
    prof.close(timeout=240.0)             # interrupts the wait
    s = prof.summary()
    assert len(s["windows"]) == 1 and not s["in_flight"]


def test_profile_report_block_always_structured():
    profiling.set_active(None)
    block = profiling.report_block()
    assert block["windows"] == [] and block["in_flight"] is False
    assert block["device_time"]["source"] == "none"
    assert block["device_time"]["total_ms"] == 0.0


def test_auto_window_armed_fires_once(tmp_path, monkeypatch):
    prof = profiling.DeviceProfiler(str(tmp_path / "dp"))
    started = []
    monkeypatch.setattr(prof, "window", lambda s: started.append(s))
    prof.arm_auto(2.5)
    prof.maybe_start_auto()
    prof.maybe_start_auto()               # one-shot: second is a no-op
    assert started == [2.5]


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_rings_are_per_thread_and_bounded():
    rec = flightrec.FlightRecorder(None, ring=4)
    for i in range(10):
        rec.mark("m", i=i)

    def worker():
        rec.log_event("INFO", "firebird.x", "from-worker")

    t = threading.Thread(target=worker, name="fr-worker")
    t.start()
    t.join()
    doc = rec.bundle("test")
    main_ring = doc["threads"][threading.current_thread().name]
    assert len(main_ring) == 4            # bounded
    assert [ev["i"] for ev in main_ring] == [6, 7, 8, 9]
    assert doc["threads"]["fr-worker"][0]["message"] == "from-worker"
    assert doc["reasons"] == ["test"]


def test_ring_events_stamp_active_batch():
    rec = flightrec.FlightRecorder(None, ring=8)
    with tracing.activate(tracing.TraceContext("rid/b2")):
        rec.mark("stage", stage="drain")
        rec.log_event("INFO", "firebird.x", "inside")
    doc = rec.bundle("test")
    ring = doc["threads"][threading.current_thread().name]
    assert all(ev["batch"] == "rid/b2" for ev in ring)


def test_dump_writes_bundle_and_counts(tmp_path, fresh_metrics):
    path = str(tmp_path / "sub" / "postmortem.json")
    rec = flightrec.FlightRecorder(path, ring=8, run_id="rid",
                                   fingerprint="fp")
    rec.mark("stage", stage="fetch")
    try:
        raise RuntimeError("boom")
    except RuntimeError as e:
        doc = rec.dump("unhandled_exception", e)
    assert doc["exception"]["type"] == "RuntimeError"
    on_disk = json.load(open(path))
    assert on_disk["schema"] == flightrec.SCHEMA
    assert on_disk["run_id"] == "rid"
    assert on_disk["config_fingerprint"] == "fp"
    assert on_disk["exception"]["message"] == "boom"
    assert obs_metrics.counter("postmortems_written").value == 1
    # repeat dumps overwrite, accumulating reasons
    rec.dump("sigterm")
    assert json.load(open(path))["reasons"] == \
        ["unhandled_exception", "sigterm"]


def test_armed_recorder_feeds_spans_without_a_tracer(tmp_path, disarmed):
    """While armed, span() records into the rings even when no tracer
    runs — a postmortem always has recent spans to show."""
    rec = flightrec.arm(None, ring=8)
    assert tracing.active() is None
    with tracing.span("drain", chips=1):
        pass
    ring = rec.bundle("t")["threads"][threading.current_thread().name]
    assert ring and ring[0]["kind"] == "span" and ring[0]["name"] == "drain"


def test_thread_excepthook_dumps(tmp_path, disarmed):
    path = str(tmp_path / "postmortem.json")
    quiet = lambda args: None             # silence the chained default hook
    orig = threading.excepthook
    threading.excepthook = quiet
    try:
        flightrec.arm(path, ring=8)

        def crash():
            raise ValueError("thread died")

        t = threading.Thread(target=crash, name="doomed")
        t.start()
        t.join()
    finally:
        flightrec.disarm()
        threading.excepthook = orig
    doc = json.load(open(path))
    assert doc["reason"] == "unhandled_exception"
    assert doc["exception"]["message"] == "thread died"


def test_watchdog_stall_triggers_postmortem(tmp_path, fresh_metrics,
                                            disarmed):
    path = str(tmp_path / "postmortem.json")
    flightrec.arm(path, ring=8, run_id="rid")
    clock = [0.0]
    wd = Watchdog(stall_sec=10.0, clock=lambda: clock[0])
    wd.beat()
    clock[0] = 11.0
    assert wd.check() is True
    doc = json.load(open(path))
    assert doc["reason"] == "watchdog_stall"
    # disarmed: a second stall in another run dumps nothing new
    flightrec.disarm()
    os.unlink(path)
    wd2 = Watchdog(stall_sec=10.0, clock=lambda: clock[0])
    wd2.beat()
    clock[0] = 22.5
    assert wd2.check() is True
    assert not os.path.exists(path)


def test_arm_disarm_restore_hooks(disarmed):
    import signal as sigmod
    import sys

    prev_except = sys.excepthook
    prev_thread = threading.excepthook
    prev_sig = sigmod.getsignal(sigmod.SIGTERM)
    flightrec.arm(None, ring=4)
    assert sys.excepthook is not prev_except
    assert threading.excepthook is not prev_thread
    assert sigmod.getsignal(sigmod.SIGTERM) is not prev_sig
    flightrec.disarm()
    assert sys.excepthook is prev_except
    assert threading.excepthook is prev_thread
    assert sigmod.getsignal(sigmod.SIGTERM) == (prev_sig or sigmod.SIG_DFL)
    assert flightrec.active() is None


def test_progress_marks_flow_from_runstatus(disarmed):
    rec = flightrec.arm(None, ring=16)
    status = obs_server.RunStatus("r", "test", chips_total=1)
    try:
        status.set_stage("dispatch")
        status.batch_dispatched()
        status.batch_done(3)
    finally:
        obs_server.clear_status()
    ring = rec.bundle("t")["threads"][threading.current_thread().name]
    kinds = [(ev["kind"], ev["name"]) for ev in ring]
    assert ("mark", "stage") in kinds
    assert ("mark", "batch_dispatched") in kinds
    assert ("mark", "batch_done") in kinds


# ---------------------------------------------------------------------------
# Watchdog throughput-drop surfacing (satellite)
# ---------------------------------------------------------------------------

def test_throughput_drop_events_surface_in_degraded_block(fresh_metrics):
    clock = [0.0]
    wd = Watchdog(stall_sec=1000.0, clock=lambda: clock[0])
    for i in range(20):
        clock[0] = float(i)
        wd.beat()
    for i in range(6):
        clock[0] = 20.0 + 5.0 * (i + 1)
        wd.beat()
    snap = wd.snapshot()
    ev = snap["throughput_drops"][0]
    # the event is operator-readable: wall-clock stamp + the crossed
    # threshold, not just two rates and a monotonic offset
    assert "at" in ev and "threshold_per_sec" in ev
    assert ev["recent_per_sec"] < ev["threshold_per_sec"]
    status = obs_server.RunStatus("r", "test", watchdog=wd)
    try:
        deg = status.degraded_block()
    finally:
        obs_server.clear_status()
    assert deg["throughput_drops"] == snap["throughput_drops"]


# ---------------------------------------------------------------------------
# End-to-end propagation: one batch id across four threads (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # ~30s (full small changedetection run); telemetry-smoke proves trace propagation across real processes in `make test`
def test_driver_trace_propagation_end_to_end(tmp_path):
    """A real (small) changedetection run: every pipeline span in
    fetch→pack→stage→dispatch→drain→d2h→store_write carries the SAME
    per-batch id across the prefetch, main, drain, and writer threads,
    JSON log lines inside a batch carry it too, and the drain histogram
    gains exemplars pointing at real batches."""
    from firebird_tpu.driver import core
    from firebird_tpu.ingest import SyntheticSource

    # Same shape/dtype as test_driver.py so the jit cache entry is shared.
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"),
                 source_backend="synthetic", chips_per_batch=1,
                 dtype="float64", device_sharding="off", fetch_retries=0,
                 trace=str(tmp_path / "trace.json"))
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)

    captured: list[str] = []

    class _Cap(logging.Handler):
        def __init__(self):
            super().__init__(logging.DEBUG)
            self._fmt = jsonlog.JsonFormatter()

        def emit(self, record):
            captured.append(self._fmt.format(record))

    fblog = logging.getLogger("firebird")
    cap = _Cap()
    fblog.addHandler(cap)
    old_level = fblog.level
    fblog.setLevel(logging.DEBUG)
    try:
        done = core.changedetection(x=100, y=200,
                                    acquired="1995-01-01/1997-06-01",
                                    number=2, chunk_size=2, cfg=cfg,
                                    source=src)
    finally:
        fblog.removeHandler(cap)
        fblog.setLevel(old_level)
    assert len(done) == 2

    rep = json.load(open(tmp_path / "obs_report.json"))
    run_id = rep["run"]["run_id"]
    trace = json.load(open(tmp_path / "trace.json"))
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    staged = [e for e in events
              if e["name"] in ("fetch", "pack", "stage", "dispatch",
                               "drain", "d2h", "store_write")]
    assert staged
    # EVERY pipeline span parents to a batch of THIS run and has a span id
    for e in staged:
        assert e["args"]["batch"].startswith(run_id + "/b"), e
        assert e["args"]["span_id"] > 0
    by_batch: dict = {}
    for e in staged:
        by_batch.setdefault(e["args"]["batch"], []).append(e)
    assert len(by_batch) == 2             # 2 chips, chips_per_batch=1
    for batch, evs in by_batch.items():
        names = {e["name"] for e in evs}
        # the full pipeline, fetch through store write, on one id
        assert {"fetch", "pack", "stage", "dispatch", "drain", "d2h",
                "store_write"} <= names, (batch, names)
        # ...across at least three OS threads (prefetch stages, the main
        # thread dispatches, the drain executor drains, a writer writes)
        tids = {e["tid"] for e in evs}
        assert len(tids) >= 3, (batch, tids)
        main_tid = next(e["tid"] for e in evs if e["name"] == "dispatch")
        assert {e["tid"] for e in evs if e["name"] == "fetch"} != {main_tid}
        assert {e["tid"] for e in evs
                if e["name"] == "store_write"} != {main_tid}

    # JSON log lines inside a batch carry the same parent id + run id
    docs = [json.loads(s) for s in captured]
    batch_lines = [d for d in docs if "batch" in d]
    assert batch_lines, "no in-context log lines captured"
    for d in batch_lines:
        assert d["batch"] in by_batch
        assert d["run_id"] == run_id

    # the drain histogram's exemplars point at real batches of this run
    ex = rep["metrics"]["histograms"]["pipeline_drain_seconds"]["exemplars"]
    assert ex and all(e["batch"] in by_batch for e in ex)

    # and the report's slo/profile blocks are structurally present
    assert "objectives" in rep["slo"]
    assert rep["profile"]["device_time"]["source"] == "none"
