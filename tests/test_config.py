from firebird_tpu.config import Config


def test_defaults():
    cfg = Config()
    assert cfg.input_parallelism == 1  # mirrors INPUT_PARTITIONS default
    assert cfg.max_obs == 512


def test_from_env():
    cfg = Config.from_env(env={"ARD_CHIPMUNK": "http://h:1/ard_x",
                               "AUX_CHIPMUNK": "http://h:1/aux_y",
                               "INPUT_PARTITIONS": "4"})
    assert cfg.ard_url.endswith("/ard_x")
    assert cfg.input_parallelism == 4


def test_keyspace_derivation():
    # Mirrors ccdc/__init__.py:29-44: keyspace = f(ard path, aux path, version)
    cfg = Config(ard_url="http://host/ard-c01-v01", aux_url="http://host/aux-c01-v01",
                 version="1.0")
    ks = cfg.keyspace()
    assert ks == "ard_c01_v01_aux_c01_v01_ccdc_1_0"
    # namespaced differently for different inputs
    cfg2 = Config(ard_url="http://host/ard-c01-v02", aux_url="http://host/aux-c01-v01",
                  version="1.0")
    assert cfg2.keyspace() != ks


def test_overrides():
    cfg = Config.from_env(env={}, chips_per_batch=16)
    assert cfg.chips_per_batch == 16
