from firebird_tpu.config import Config


def test_defaults():
    cfg = Config()
    assert cfg.input_parallelism == 1  # mirrors INPUT_PARTITIONS default
    assert cfg.max_obs == 512


def test_from_env():
    cfg = Config.from_env(env={"ARD_CHIPMUNK": "http://h:1/ard_x",
                               "AUX_CHIPMUNK": "http://h:1/aux_y",
                               "INPUT_PARTITIONS": "4"})
    assert cfg.ard_url.endswith("/ard_x")
    assert cfg.input_parallelism == 4


def test_keyspace_derivation():
    # Mirrors ccdc/__init__.py:29-44: keyspace = f(ard path, aux path, version)
    cfg = Config(ard_url="http://host/ard-c01-v01", aux_url="http://host/aux-c01-v01",
                 version="1.0")
    ks = cfg.keyspace()
    assert ks == "ard_c01_v01_aux_c01_v01_ccdc_1_0"
    # namespaced differently for different inputs
    cfg2 = Config(ard_url="http://host/ard-c01-v02", aux_url="http://host/aux-c01-v01",
                  version="1.0")
    assert cfg2.keyspace() != ks


def test_overrides():
    cfg = Config.from_env(env={}, chips_per_batch=16)
    assert cfg.chips_per_batch == 16


def test_knob_defaults_agree_with_config_defaults():
    # A knob that declares BOTH a Config field and a registry default has
    # two homes for that default (Knob.default feeds env_knob readers,
    # the Config field feeds from_env's fallback).  Keep them in
    # agreement: setting the env var to its own registry default must be
    # a no-op on the resulting Config.
    from firebird_tpu.config import KNOBS

    baseline = Config.from_env(env={})
    for knob in KNOBS:
        if knob.field is None or knob.default is None:
            continue
        pinned = Config.from_env(env={knob.name: knob.default})
        assert getattr(pinned, knob.field) == getattr(baseline, knob.field), (
            f"{knob.name}: registry default {knob.default!r} disagrees "
            f"with Config.{knob.field} default "
            f"{getattr(baseline, knob.field)!r}")


def test_obs_merge_timeout_zero_means_merge_now():
    # 0 = "merge whatever shards already arrived, don't wait" — a valid
    # operator setting the validation must not reject.
    cfg = Config.from_env(env={"FIREBIRD_OBS_MERGE_TIMEOUT": "0"})
    assert cfg.obs_merge_timeout == 0.0
