"""Grid geometry tests.

Golden values come from the reference's recorded Chipmunk responses
(test/data/{grid,snap,near}_response.json): the tile grid maps proj
(-615585, 2414805) <-> grid (13, 6) and the chip grid maps
(-543585, 2378805) <-> (674, 312).
"""

import numpy as np

from firebird_tpu import grid


def test_definition_shape():
    defn = grid.CONUS.definition()
    assert {d["name"] for d in defn} == {"tile", "chip"}
    assert set(defn[0].keys()) == {"proj", "tx", "sy", "ty", "ry", "rx", "sx", "name"}
    tiledef = next(d for d in defn if d["name"] == "tile")
    assert tiledef["sx"] == 150000.0 and tiledef["tx"] == 2565585.0


def test_grid_pt_proj_pt_roundtrip_tile():
    # Golden pair from snap_response.json
    assert grid.grid_pt(-615585.0, 2414805.0, grid.CONUS_TILE) == (13, 6)
    assert grid.proj_pt(13, 6, grid.CONUS_TILE) == (-615585.0, 2414805.0)


def test_grid_pt_proj_pt_roundtrip_chip():
    assert grid.grid_pt(-543585.0, 2378805.0, grid.CONUS_CHIP) == (674, 312)
    assert grid.proj_pt(674, 312, grid.CONUS_CHIP) == (-543585.0, 2378805.0)


def test_snap_interior_point():
    # Any point interior to chip (674, 312) snaps to its UL corner.
    s = grid.snap(-543585.0 + 1500.0, 2378805.0 - 1500.0)
    assert s["chip"]["proj-pt"] == (-543585.0, 2378805.0)
    assert s["chip"]["grid-pt"] == (674, 312)
    # ... and to the containing tile (13, 6).
    assert s["tile"]["proj-pt"] == (-615585.0, 2414805.0)
    assert s["tile"]["grid-pt"] == (13, 6)


def test_tile_record():
    t = grid.tile(100, 200)
    assert set(t.keys()) == {"x", "y", "h", "v", "ulx", "uly", "lrx", "lry", "chips"}
    # 100, 200 falls in tile h=17, v=20 region? Verify self-consistency.
    assert t["ulx"] == t["x"] and t["uly"] == t["y"]
    assert t["lrx"] - t["ulx"] == 150000.0
    assert t["uly"] - t["lry"] == 150000.0
    assert t["ulx"] <= 100 < t["lrx"]
    assert t["lry"] < 200 <= t["uly"]
    # A tile contains exactly 50x50 = 2500 chips (SURVEY.md §0).
    assert t["chips"].shape == (2500, 2)
    # First chip is the tile's UL corner; chips step by 3000 m.
    assert tuple(t["chips"][0]) == (t["ulx"], t["uly"])
    assert tuple(t["chips"][1]) == (t["ulx"] + 3000, t["uly"])
    assert tuple(t["chips"][50]) == (t["ulx"], t["uly"] - 3000)
    # All chips are inside the tile extents.
    assert t["chips"][:, 0].min() == t["ulx"]
    assert t["chips"][:, 0].max() == t["lrx"] - 3000
    assert t["chips"][:, 1].max() == t["uly"]
    assert t["chips"][:, 1].min() == t["lry"] + 3000


def test_chips_ints():
    cs = grid.chips(grid.tile(-543585.0, 2378805.0))
    assert len(cs) == 2500
    assert all(isinstance(c[0], int) and isinstance(c[1], int) for c in cs)
    assert (-543585, 2378805) in cs


def test_near_is_3x3():
    n = grid.near(-543585.0, 2378805.0)
    assert len(n["tile"]) == 9
    assert len(n["chip"]) == 9
    hs = sorted({gp["grid-pt"][0] for gp in n["tile"]})
    vs = sorted({gp["grid-pt"][1] for gp in n["tile"]})
    assert hs == [12, 13, 14]
    assert vs == [5, 6, 7]
    # Ordering matches the reference fixture: h ascending outer, proj-y
    # ascending inner (near_response.json).
    assert n["tile"][0]["grid-pt"] == (12, 7)
    assert n["tile"][1]["grid-pt"] == (12, 6)
    assert n["tile"][-1]["grid-pt"] == (14, 5)


def test_training_is_nine_tiles():
    # ref test/test_grid.py:18-20 asserts 9 tiles worth of chips.
    cids = grid.training(-543585.0, 2378805.0)
    assert len(cids) == 9 * 2500
    assert len(set(cids)) == 9 * 2500


def test_classification_is_one_tile():
    cids = grid.classification(-543585.0, 2378805.0)
    assert len(cids) == 2500
    assert (-543585, 2378805) in cids


def test_coordinates_dtype():
    t = grid.tile(0, 0)
    assert t["chips"].dtype == np.int64


def test_tiles_for_bounds_single_point():
    recs = grid.tiles_for_bounds([(-543585.0, 2378805.0)])
    assert len(recs) == 1
    r = recs[0]
    assert (r["h"], r["v"]) == (13, 6)
    # extents match the tile record for the same point
    t = grid.tile(-543585.0, 2378805.0)
    assert (r["ulx"], r["uly"], r["lrx"], r["lry"]) == (
        t["ulx"], t["uly"], t["lrx"], t["lry"])


def test_tiles_for_bounds_bbox_and_order():
    # span two tiles in h and two in v -> 4 tiles, row-major v-then-h
    x0, y0 = -543585.0, 2378805.0
    recs = grid.tiles_for_bounds([(x0, y0), (x0 + 150000.0, y0 - 150000.0)])
    assert [(r["h"], r["v"]) for r in recs] == [
        (13, 6), (14, 6), (13, 7), (14, 7)]
    # every tile's extents contain no gaps: widths are the tile spacing
    for r in recs:
        assert r["lrx"] - r["ulx"] == 150000.0
        assert r["uly"] - r["lry"] == 150000.0
