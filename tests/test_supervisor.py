"""Elastic fleet: scale policy + supervisor mechanics, deterministically.

The test_fleet.py discipline applied to autoscaling: every hysteresis
window, park backoff, adoption pass, and retire deadline is exact
arithmetic on an injectable clock — no subprocesses, no sleeps.  The
live 726-tile kill/partition/supervisor-restart proof is
tools/elastic_soak.py (`make elastic-smoke`).
"""

import os
import random

import pytest

from firebird_tpu.config import Config
from firebird_tpu.fleet import (FleetQueue, FleetWorker, QueueSnapshot,
                                ScalePolicy, Supervisor)
from firebird_tpu.obs import metrics as obs_metrics


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def clock():
    return Clock()


@pytest.fixture
def queue(tmp_path, clock):
    q = FleetQueue(str(tmp_path / "fleet.db"), lease_sec=30.0, clock=clock)
    yield q
    q.close()


@pytest.fixture(autouse=True)
def _fresh_registry():
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


def snap(clock, *, claimable=0, pending=None, leased=0, dead=0, blocked=0,
         oldest=0.0, rate=0.0, stream_open=0) -> QueueSnapshot:
    """Hand-built snapshot: pending defaults to claimable + blocked."""
    return QueueSnapshot(
        at=clock(), by_type={},
        claimable=claimable,
        pending=claimable + blocked if pending is None else pending,
        leased=leased, dead=dead, blocked=blocked,
        oldest_lease_age_sec=oldest, drain_rate_per_sec=rate,
        drain_window_sec=60.0, stream_open=stream_open)


def policy(clock, min_w=0, max_w=10, **kw) -> ScalePolicy:
    kw.setdefault("jobs_per_worker", 2.0)
    kw.setdefault("up_after_sec", 3.0)
    kw.setdefault("idle_after_sec", 10.0)
    kw.setdefault("rng", random.Random(7))
    return ScalePolicy(min_w, max_w, clock=clock, **kw)


# ---------------------------------------------------------------------------
# ScalePolicy boundary cases
# ---------------------------------------------------------------------------

def test_scale_up_needs_sustained_backlog(clock):
    p = policy(clock)
    d = p.decide(snap(clock, claimable=20), live=0)
    assert d.target == 0 and d.want == 10       # demand seen, held
    clock.advance(2.0)
    assert p.decide(snap(clock, claimable=20), live=0).target == 0
    clock.advance(1.5)                          # 3.5s > up_after_sec
    d = p.decide(snap(clock, claimable=20), live=0)
    assert d.target == 10
    assert "scale up" in d.reason


def test_hysteresis_suppresses_flapping(clock):
    """Backlog that appears and vanishes inside the windows never moves
    the target: the up-timer resets on every idle reading and the
    down-timer resets on every busy reading."""
    p = policy(clock)
    live = 2
    for _ in range(20):
        d = p.decide(snap(clock, claimable=20), live=live)
        assert d.target == live                 # up-window never elapses
        clock.advance(2.0)
        d = p.decide(snap(clock), live=live)    # empty inside idle window
        assert d.target == live                 # down-window never elapses
        clock.advance(2.0)


def test_min_equals_max_pins_fleet(clock):
    p = policy(clock, min_w=4, max_w=4)
    for s in (snap(clock), snap(clock, claimable=1000),
              snap(clock, dead=50)):
        d = p.decide(s, live=4)
        assert d.target == 4 and "pinned" in d.reason
    # Pinning holds across time too — no window ever scales it.
    clock.advance(100.0)
    assert p.decide(snap(clock), live=4).target == 4


def test_scale_to_zero_needs_empty_depth_and_no_leases(clock):
    p = policy(clock, idle_after_sec=5.0)
    # An open lease blocks scale-to-zero even with nothing claimable.
    d = p.decide(snap(clock, claimable=0, leased=1), live=1)
    assert d.target == 1 and d.want == 1
    clock.advance(60.0)
    assert p.decide(snap(clock, claimable=0, leased=1), live=1).target == 1
    # Pending-but-blocked work with NO lease in flight is wedged (no
    # ack can unblock it): held through the idle window, then zero.
    assert p.decide(snap(clock, claimable=0, blocked=3),
                    live=1).target == 1
    # Truly empty: zero only after the idle window.
    d = p.decide(snap(clock), live=1)
    assert d.target == 1                        # idle timer just started
    clock.advance(6.0)
    d = p.decide(snap(clock), live=1)
    assert d.target == 0 and "zero" in d.reason


def test_dead_letters_do_not_inflate_target(clock):
    """A dead-letter-dominated queue must not pin the fleet at max:
    demand counts only claimable + leased work."""
    p = policy(clock, up_after_sec=0.0)
    d = p.decide(snap(clock, claimable=2, dead=5000), live=0)
    assert d.target == 1 and d.want == 1        # ceil(2/2), not max
    # All-dead queue with blocked pending jobs and no lease: wedged —
    # zero demand (held through the idle window), never a fleet.
    d = p.decide(snap(clock, claimable=0, blocked=4, dead=5000), live=1)
    assert d.target == 1 and d.want == 0


def test_wedged_queue_demands_zero_workers(clock):
    """claimable==0, leased==0, pending>0 is FleetQueue.wedged()'s
    verdict: no ack can ever unblock the pending work, so demand is 0
    and the fleet scales to zero after the idle window instead of
    spawning workers that exit wedged forever."""
    p = policy(clock, idle_after_sec=5.0)
    d = p.decide(snap(clock, claimable=0, blocked=7, dead=3), live=2)
    assert d.want == 0 and d.target == 2        # idle window holds
    clock.advance(6.0)
    d = p.decide(snap(clock, claimable=0, blocked=7, dead=3), live=2)
    assert d.target == 0 and "wedged" in d.reason
    # A lease in flight is NOT wedged: its ack may unblock the DAG.
    assert p.decide(snap(clock, claimable=0, blocked=7, leased=1),
                    live=1).want == 1


def test_crash_loop_parks_slot_with_backoff_and_expires(clock):
    p = policy(clock, max_w=5, crash_limit=3, crash_window_sec=60.0,
               park_base_sec=10.0, park_cap_sec=100.0, up_after_sec=0.0)
    assert not p.record_exit(1)
    assert not p.record_exit(None)              # vanished = abnormal
    assert p.record_exit(1)                     # third in window: trips
    assert len(p.parks()) == 1
    d = p.decide(snap(clock, claimable=100), live=0)
    assert d.target == 4 and d.parked == 1      # cap shrank by one
    # Park expires after its backoff delay: capacity returns.
    delay = p.parks()[0]["delay_sec"]
    clock.advance(delay + 0.1)
    d = p.decide(snap(clock, claimable=100), live=4)
    assert d.parked == 0 and d.target == 5
    # A second burst parks again, with a (jittered) longer-or-equal
    # delay drawn through retry.decorrelated_delay.
    for _ in range(3):
        p.record_exit(9)
    assert len(p.parks()) == 1
    assert p.parks()[0]["delay_sec"] >= 10.0


def test_parks_survive_queue_wall_clock_snapshots(clock):
    """Regression: parks are stamped on the POLICY clock (monotonic in
    production) while snapshots ride the queue's wall clock — a decide()
    sweeping parks against snap.at would expire every park instantly
    (monotonic seconds are tiny next to epoch seconds)."""
    p = policy(clock, max_w=5, crash_limit=1, park_base_sec=50.0,
               up_after_sec=0.0)
    p.record_exit(1)                            # trips immediately
    assert len(p.parks()) == 1
    wall = QueueSnapshot(
        at=1.75e9, by_type={}, claimable=100, pending=100, leased=0,
        dead=0, blocked=0, oldest_lease_age_sec=0.0,
        drain_rate_per_sec=0.0, drain_window_sec=60.0, stream_open=0)
    d = p.decide(wall, live=0)
    assert d.parked == 1 and d.target == 4      # park still in force


def test_clean_exit_resets_crash_burst(clock):
    p = policy(clock, crash_limit=3)
    p.record_exit(1)
    p.record_exit(1)
    p.record_exit(0)                            # clean exit resets
    assert not p.record_exit(1)                 # burst starts over
    assert p.parks() == []


def test_crash_window_expires_old_exits(clock):
    p = policy(clock, crash_limit=3, crash_window_sec=60.0)
    p.record_exit(1)
    p.record_exit(1)
    clock.advance(61.0)                         # both age out
    assert not p.record_exit(1)
    assert p.parks() == []


def test_policy_validation():
    with pytest.raises(ValueError, match="min_workers"):
        ScalePolicy(-1, 5)
    with pytest.raises(ValueError, match="max_workers"):
        ScalePolicy(4, 2)
    with pytest.raises(ValueError, match="max_workers"):
        ScalePolicy(0, 0)
    with pytest.raises(ValueError, match="jobs_per_worker"):
        ScalePolicy(0, 5, jobs_per_worker=0)


def test_config_fleet_worker_bounds():
    with pytest.raises(ValueError, match="MIN_WORKERS"):
        Config(fleet_min_workers=-1)
    with pytest.raises(ValueError, match="MAX_WORKERS"):
        Config(fleet_min_workers=5, fleet_max_workers=3)
    with pytest.raises(ValueError, match="GRACE"):
        Config(fleet_grace_sec=0)
    cfg = Config.from_env(env={"FIREBIRD_FLEET_MIN_WORKERS": "2",
                               "FIREBIRD_FLEET_MAX_WORKERS": "12",
                               "FIREBIRD_FLEET_GRACE_SEC": "9"})
    assert (cfg.fleet_min_workers, cfg.fleet_max_workers,
            cfg.fleet_grace_sec) == (2, 12, 9.0)


# ---------------------------------------------------------------------------
# Queue: scale snapshot + worker registry + supervisor heartbeat
# ---------------------------------------------------------------------------

def test_scale_snapshot_is_pressure_reading(queue, clock):
    d1 = queue.enqueue("detect", {"n": 1})
    queue.enqueue("detect", {"n": 2})
    queue.enqueue("classify", {}, depends_on=[d1])   # blocked
    queue.enqueue("stream", {"cx": 1, "cy": 2})      # separate pool
    lease = queue.claim("w")                         # leases d1
    clock.advance(10.0)
    s = queue.scale_snapshot(window_sec=60.0)
    assert s.claimable == 1                          # d2 only
    assert s.leased == 1 and s.blocked == 1
    assert s.stream_open == 1
    assert s.backlog == 2
    assert s.oldest_lease_age_sec == 10.0
    assert s.drain_rate_per_sec == 0.0
    assert s.drain_eta_sec() is None                 # no rate evidence
    queue.ack(lease)                                 # unblocks classify
    s = queue.scale_snapshot(window_sec=60.0)
    assert s.claimable == 2 and s.blocked == 0
    assert s.drain_rate_per_sec == pytest.approx(1 / 60.0)
    assert s.drain_eta_sec() == pytest.approx(120.0)  # 2 open / rate
    # Acks age out of the trailing window.
    clock.advance(61.0)
    assert queue.scale_snapshot(window_sec=60.0).drain_rate_per_sec == 0.0


def test_scale_snapshot_counts_expired_lease_once(queue, clock):
    """Regression: a mass-killed fleet leaves jobs 'leased' with
    expired leases — re-claimable work that must count ONCE in backlog
    (as claimable), not twice (claimable AND leased)."""
    for i in range(4):
        queue.enqueue("detect", {"n": i})
    for _ in range(4):
        queue.claim("doomed")
    clock.advance(31.0)                          # all 4 leases expire
    s = queue.scale_snapshot(window_sec=60.0)
    assert s.claimable == 4 and s.leased == 0
    assert s.backlog == 4                        # not 8


def test_worker_registry_roundtrip(queue, clock):
    queue.worker_register("h:11", 11, kind="batch", host="h")
    queue.enqueue("detect", {})
    queue.claim("h:11")
    clock.advance(5.0)
    queue.worker_beat("h:11", acked=7)
    (row,) = queue.workers()
    assert row["pid"] == 11 and row["acked"] == 7
    assert row["up_sec"] == 5.0 and row["beat_age_sec"] == 0.0
    assert row["lease"]["type"] == "detect"
    assert row["lease"]["age_sec"] == 5.0
    assert queue.workers(kind="stream") == []
    # Re-registration refreshes, never duplicates or zeroes the tally.
    queue.worker_register("h:11", 11, kind="batch", host="h")
    (row,) = queue.workers()
    assert row["acked"] == 7
    queue.worker_deregister("h:11")
    assert queue.workers() == []
    # Beat on a pruned row is a no-op, not a resurrection.
    queue.worker_beat("h:11", acked=9)
    assert queue.workers() == []


def test_supervisor_heartbeat_persists(queue, clock):
    assert queue.supervisor_state() is None
    queue.supervisor_heartbeat({"target": 3, "live": 2, "pid": 42})
    clock.advance(4.0)
    st = queue.supervisor_state()
    assert st["target"] == 3 and st["pid"] == 42
    assert st["beat_age_sec"] == 4.0
    assert queue.status()["supervisor"]["target"] == 3


def test_worker_run_registers_and_deregisters(queue, clock):
    cfg = Config(store_backend="sqlite", store_path="unused.db",
                 fleet_db=queue.path)
    seen = {}

    def handler(payload, lease):
        seen["workers"] = queue.workers()

    queue.enqueue("detect", {"cids": []})
    w = FleetWorker(cfg, queue, handlers={"detect": handler},
                    clock=clock, sleep=lambda s: None)
    w.run()
    # Registered while running (the handler saw its own row), clean
    # exit removed the row.
    (row,) = seen["workers"]
    assert row["pid"] == os.getpid() and row["kind"] == "batch"
    assert queue.workers() == []


# ---------------------------------------------------------------------------
# Supervisor mechanics (fake spawner, injectable clock)
# ---------------------------------------------------------------------------

class FakeProc:
    """Popen-shaped: pid, poll, send_signal — plus test hooks."""

    _pids = iter(range(50000, 60000))

    def __init__(self):
        self.pid = next(FakeProc._pids)
        self.returncode = None
        self.signals = []

    def poll(self):
        return self.returncode

    def send_signal(self, sig):
        self.signals.append(int(sig))


@pytest.fixture
def harness(tmp_path, queue, clock):
    spawned = []

    def spawn():
        p = FakeProc()
        spawned.append(p)
        return p

    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "s.db"), fleet_db=queue.path)
    sup = Supervisor(
        cfg, queue,
        policy=ScalePolicy(0, 5, jobs_per_worker=2.0, up_after_sec=0.0,
                           idle_after_sec=10.0, clock=clock,
                           rng=random.Random(3)),
        spawn=spawn, grace_sec=20.0, clock=clock, sleep=lambda s: None,
        # The fake queue clock's registration stamps are not wall
        # times, so real /proc start times would misread every row as
        # recycled; "unknown" takes the age guard out of these tests
        # (test_supervisor_refuses_recycled_pid injects real values).
        proc_start=lambda pid: None)
    return sup, spawned


def test_supervisor_spawns_to_target(harness, queue, clock):
    sup, spawned = harness
    for i in range(6):
        queue.enqueue("detect", {"n": i})
    st = sup.tick()
    assert len(spawned) == 3                     # ceil(6/2)
    assert st["target"] == 3 and st["live"] == 3
    assert obs_metrics.gauge("fleet_workers_target").value == 3
    assert obs_metrics.gauge("fleet_workers_live").value == 3
    assert obs_metrics.counter("fleet_scale_up_total").value == 1
    # Steady state: no double-spawn on the next tick.
    clock.advance(1.0)
    sup.tick()
    assert len(spawned) == 3


def test_supervisor_retires_gracefully_then_kills(harness, queue, clock):
    import signal as sig

    sup, spawned = harness
    for i in range(6):
        queue.enqueue("detect", {"n": i})
    sup.tick()
    # Drain everything; idle window elapses -> scale to zero.
    while True:
        lease = queue.claim("w")
        if lease is None:
            break
        queue.ack(lease)
    clock.advance(1.0)
    sup.tick()                                   # idle timer starts
    clock.advance(11.0)
    st = sup.tick()
    assert st["target"] == 0 and st["retiring"] == 3
    assert all(p.signals == [sig.SIGTERM] for p in spawned)
    assert obs_metrics.counter("fleet_scale_down_total").value == 1
    # Within grace: no SIGKILL yet.
    clock.advance(5.0)
    sup.tick()
    assert all(sig.SIGKILL not in p.signals for p in spawned)
    # Past grace: escalation.
    clock.advance(16.0)
    sup.tick()
    assert all(p.signals == [sig.SIGTERM, sig.SIGKILL] for p in spawned)
    # They die; the registry of workers empties and run() would exit.
    for p in spawned:
        p.returncode = -9
    sup.tick()
    assert sup.workers == {}


def test_supervisor_adopts_orphans_not_double_spawns(harness, queue, clock):
    """A restarted supervisor must adopt live registered workers (by
    pid) instead of spawning a second fleet over them."""
    from firebird_tpu.obs import jsonlog

    sup, spawned = harness
    queue.worker_register("h:live", os.getpid(), kind="batch",
                          host=jsonlog.HOST)
    for i in range(4):
        queue.enqueue("detect", {"n": i})
    st = sup.tick()
    # Target 2 = ceil(4/2); one slot is the adopted orphan (our own live
    # pid), so only ONE new worker spawns.
    assert st["adopted_total"] == 1
    assert len(spawned) == 1
    assert st["live"] == 2
    # Stream workers are a separate pool: never adopted as batch.
    queue.worker_register("h:stream", os.getpid() + 1, kind="stream")
    clock.advance(1.0)
    st = sup.tick()
    assert st["adopted_total"] == 1


def test_supervisor_refuses_recycled_pid(harness, queue, clock):
    """A registry row whose pid names a process that started AFTER the
    row was written is a recycled pid (an unrelated process wearing a
    dead worker's number): pruned, never adopted or signalled."""
    from firebird_tpu.obs import jsonlog

    sup, spawned = harness
    queue.worker_register("h:old", os.getpid(), kind="batch",
                          host=jsonlog.HOST)
    (row,) = queue.workers()
    sup._proc_start = lambda pid: row["started"] + 100.0
    sup.tick()
    assert sup.workers == {}                     # never adopted
    assert queue.workers() == []                 # row pruned
    # A start time BEFORE registration is the legitimate case: adopt.
    queue.worker_register("h:new", os.getpid(), kind="batch",
                          host=jsonlog.HOST)
    (row,) = queue.workers()
    sup._proc_start = lambda pid: row["started"] - 1.0
    clock.advance(1.0)
    st = sup.tick()
    assert st["adopted_total"] == 1


def test_retired_worker_exit_is_not_circuit_food(harness, queue, clock):
    """A worker the supervisor itself retired — even one it SIGKILLed
    past grace — must not feed the crash-loop circuit: deliberate
    escalation is not a crash-looping payload."""
    import signal as sig

    sup, spawned = harness
    for i in range(10):
        queue.enqueue("detect", {"n": i})
    sup.tick()
    assert len(spawned) == 5
    # Drain; idle window elapses; all 5 retire.
    while True:
        lease = queue.claim("w")
        if lease is None:
            break
        queue.ack(lease)
    sup.tick()
    clock.advance(11.0)
    sup.tick()
    # All ignore SIGTERM past grace: the supervisor SIGKILLs all 5
    # inside one crash window — and the circuit must NOT trip.
    clock.advance(21.0)
    sup.tick()
    for p in spawned:
        assert sig.SIGKILL in p.signals
        p.returncode = -9
    clock.advance(1.0)
    st = sup.tick()
    assert st["tallies"]["crashed"] == 0
    assert st["tallies"]["parked"] == 0 and st["parks"] == []


def test_supervisor_ignores_foreign_host_rows(harness, queue, clock):
    """Rows registered from OTHER hosts (shared queue db) are another
    supervisor's: their pid numbers mean nothing locally — never
    adopted, never signalled, never pruned."""
    sup, spawned = harness
    queue.worker_register("far:123", os.getpid(), kind="batch",
                          host="some-other-host")
    st = sup.tick()
    assert st["adopted_total"] == 0 and sup.workers == {}
    (row,) = queue.workers()                     # row untouched
    assert row["host"] == "some-other-host"


def test_supervisor_prunes_dead_rows_and_counts_crash(harness, queue,
                                                     clock):
    """A registry row whose pid is gone is an abnormal exit: the row is
    pruned so re-delivery accounting stays clean."""
    sup, spawned = harness
    queue.worker_register("h:dead", 2 ** 22 + 12345, kind="batch")
    sup.tick()
    assert queue.workers() == []                 # pruned
    assert sup.workers == {}                     # never adopted


def test_supervisor_crash_loop_parks(harness, queue, clock):
    import signal as sig

    sup, spawned = harness
    for i in range(50):
        queue.enqueue("detect", {"n": i})
    sup.tick()
    assert len(spawned) == 5
    # Kill the whole fleet abnormally, three bursts: the circuit trips
    # (crash_limit=3) and capacity shrinks below max on the respawn.
    for p in spawned[:3]:
        p.returncode = 1
    clock.advance(1.0)
    st = sup.tick()
    assert st["tallies"]["crashed"] == 3
    assert st["tallies"]["parked"] >= 1
    assert len(st["parks"]) >= 1
    assert obs_metrics.counter("fleet_scale_park_total").value >= 1
    # Live + newly spawned stays under the parked cap.
    assert st["live"] <= 5 - len(st["parks"])


def test_supervisor_run_until_drained_scales_to_zero(harness, queue,
                                                     clock):
    """run(until_drained=True) exits only after the queue drained AND
    every worker retired/exited — the scale-to-zero proof shape."""
    sup, spawned = harness
    queue.enqueue("detect", {"n": 0})

    def sleep(sec):
        # The world advances between ticks: workers drain the queue,
        # then exit cleanly once it is empty (the --until-drained
        # worker behavior), while the clock moves past every window.
        lease = queue.claim("w")
        if lease is not None:
            queue.ack(lease)
        elif queue.drained():
            for p in spawned:
                if p.returncode is None and sig_count(p):
                    p.returncode = 0
        clock.advance(4.0)

    def sig_count(p):
        import signal as sig
        return sig.SIGTERM in p.signals

    sup._sleep = sleep
    summary = sup.run(until_drained=True)
    assert summary["queue"]["done"] == 1
    assert sup.workers == {}
    assert not summary["wedged"]
    st = queue.supervisor_state()
    assert st["target"] == 0 and st["live"] == 0
    assert obs_metrics.gauge("fleet_workers_live").value == 0
    assert any("scale to zero" in d["reason"] for d in summary["decisions"])


def test_supervisor_run_wedged_exits(harness, queue, clock):
    """Pending work blocked behind a dead letter with nothing live:
    spawning more workers cannot help — run() exits wedged."""
    sup, spawned = harness
    d = queue.enqueue("detect", {}, max_attempts=1)
    queue.enqueue("classify", {}, depends_on=[d])
    lease = queue.claim("w")
    queue.fail(lease, RuntimeError("boom"))      # dead-letters d

    def sleep(sec):
        for p in spawned:
            if p.returncode is None:
                p.returncode = 4                 # workers exit wedged
        clock.advance(2.0)

    sup._sleep = sleep
    summary = sup.run(until_drained=True)
    assert summary["wedged"]


def test_drain_eta_gauge_feeds_slo(harness, queue, clock):
    from firebird_tpu.obs import slo as slomod

    sup, spawned = harness
    for i in range(4):
        queue.enqueue("detect", {"n": i})
    lease = queue.claim("w")
    queue.ack(lease)                             # rate evidence
    sup.tick()
    g = obs_metrics.gauge("queue_drain_eta_seconds").value
    assert g == pytest.approx(3 / (1 / 60.0))    # 3 open / (1 ack/60s)
    verdict = slomod.evaluate_snapshot(
        obs_metrics.get_registry().snapshot(), spec="drain_eta=10000")
    (obj,) = verdict["objectives"]
    assert obj["name"] == "drain_eta" and obj["ok"] is True
    verdict = slomod.evaluate_snapshot(
        obs_metrics.get_registry().snapshot(), spec="drain_eta=10")
    assert verdict["ok"] is False


def test_worker_cmd_floor_workers_do_not_self_exit(harness, tmp_path,
                                                   queue, clock):
    """A min_workers floor must be held by workers that poll idle:
    --until-drained floor workers would exit the moment the queue
    empties and the supervisor would respawn them forever (spawn/exit
    churn on an idle queue) — floored fleets spawn --hold-idle."""
    sup, _ = harness                             # min 0
    assert "--until-drained" in sup._worker_cmd()
    assert "--hold-idle" not in sup._worker_cmd()
    assert "--drain-on-term" in sup._worker_cmd()
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    floored = Supervisor(
        cfg, queue, policy=ScalePolicy(1, 5, clock=clock),
        spawn=lambda: None, clock=clock, sleep=lambda s: None)
    assert "--until-drained" not in floored._worker_cmd()
    assert "--hold-idle" in floored._worker_cmd()
    assert "--drain-on-term" in floored._worker_cmd()


def test_hold_idle_worker_polls_empty_queue_as_batch(tmp_path, queue):
    """`fleet work --hold-idle` must NOT exit on an empty queue (the
    floor-churn bug: a plain batch worker breaks on its first failed
    claim) and must register kind=batch so the policy counts it as
    drain capacity, unlike --forever's kind=stream."""
    import threading

    from firebird_tpu.fleet.worker import FleetWorker

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    polls = []

    def nap(sec):
        polls.append(sec)
        if len(polls) >= 3:          # held through 3 empty claims
            stop.set()

    worker = FleetWorker(cfg, queue, kind="batch", sleep=nap)
    stop = threading.Event()
    # The CLI maps --hold-idle to run(forever=True) with kind="batch"
    # (cli.fleet_work); an empty queue must poll, not break.
    summary = worker.run(forever=True, stop=stop)
    assert len(polls) >= 3 and summary["executed"] == 0
    assert not summary["wedged"]


def test_spawn_capped_by_retiring_processes(harness, queue, clock):
    """Retiring workers are still processes: a retire-then-burst cycle
    must not transiently run ~2x max_workers on the host."""
    sup, spawned = harness                       # max 5
    for i in range(10):
        queue.enqueue("detect", {"n": i})
    sup.tick()
    assert len(spawned) == 5                     # ceil(10/2), at max
    sup._retire(5)                               # all draining, all alive
    clock.advance(1.0)
    sup.tick()
    # Demand still wants 5 and live is 0, but 5 processes are draining:
    # no headroom, no spawn.
    assert len(spawned) == 5
    for p in spawned:                            # drains finish
        p.returncode = 0
    clock.advance(1.0)
    sup.tick()
    assert len(spawned) == 10                    # headroom restored
    assert len(sup.workers) == 5


def test_policy_parks_is_read_only(clock):
    """parks() runs on the ops HTTP thread concurrently with the tick
    thread's record_exit: it must never rebind/sweep _parks (a racing
    sweep could drop a just-appended park).  decide() sweeps."""
    p = policy(clock)
    now = clock()
    for _ in range(3):
        assert not p.record_exit(1, now=now) or True
    assert len(p._parks) == 1                    # circuit tripped
    inner = p._parks
    clock.advance(10_000.0)                      # way past any park cap
    assert p.parks() == []                       # expired: filtered out
    assert p._parks is inner and len(inner) == 1  # ...but NOT swept
    p.decide(snap(clock), live=0)                # tick thread sweeps
    assert p._parks == []


def test_drain_out_escalates_before_exit(harness, queue, clock):
    """Operator stop: drain_out must wait out the SIGTERM grace and
    actually SIGKILL a wedged worker before the supervisor exits —
    otherwise the worker outlives its supervisor as an orphan."""
    import signal as sig

    sup, spawned = harness                       # grace 20
    for i in range(4):
        queue.enqueue("detect", {"n": i})
    sup.tick()
    assert len(spawned) == 2
    sup._sleep = clock.advance                   # drain_out's clock
    assert sup.drain_out(timeout=60.0) is False  # they never die
    for p in spawned:
        assert p.signals[0] == sig.SIGTERM
        assert sig.SIGKILL in p.signals          # escalation ran
    assert sup.tallies["killed"] == 2
    for p in spawned:
        p.returncode = -9
    assert sup.drain_out(timeout=5.0) is True
    assert sup.workers == {}
    # Supervisor-initiated retirement, however it ended: not circuit food.
    assert sup.tallies["crashed"] == 0


def test_until_drained_exits_through_min_floor(tmp_path, queue, clock):
    """--until-drained with min_workers > 0: the floor does not hold
    past a full drain — run() retires the floor worker ONCE (no
    spawn/retire churn) and exits recording scale-to-zero."""
    import signal as sig

    spawned = []

    def spawn():
        p = FakeProc()
        spawned.append(p)
        return p

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    sup = Supervisor(
        cfg, queue,
        policy=ScalePolicy(1, 5, up_after_sec=0.0, idle_after_sec=10.0,
                           clock=clock, rng=random.Random(3)),
        spawn=spawn, grace_sec=20.0, clock=clock,
        proc_start=lambda pid: None)

    def sleep(sec):
        for p in spawned:                        # drain-on-term exit
            if p.returncode is None and sig.SIGTERM in p.signals:
                p.returncode = 0
        clock.advance(2.0)

    sup._sleep = sleep
    summary = sup.run(until_drained=True)
    assert len(spawned) == 1                     # the floor, exactly once
    assert summary["retired"] == 1 and not summary["wedged"]
    assert sup.workers == {}
    st = queue.supervisor_state()
    assert st["target"] == 0 and st["live"] == 0
    assert any("scale to zero" in d["reason"] for d in summary["decisions"])


def test_wedged_exit_is_not_crash_circuit_food(harness, queue, clock):
    """A worker exiting WEDGED_EXIT made a deliberate self-report
    (pending work all blocked behind dead deps): counting it as a
    crash would trip the circuit and park slots for a condition
    backoff cannot fix."""
    from firebird_tpu.fleet import WEDGED_EXIT

    sup, spawned = harness
    for _ in range(4):                           # 4 wedged exits > limit
        queue.enqueue("detect", {"n": 1})
        sup.tick()
        for p in spawned:
            if p.returncode is None:
                p.returncode = WEDGED_EXIT
        # Drain the queue so the next tick's spawn has fresh demand.
        lease = queue.claim("w")
        if lease is not None:
            queue.ack(lease)
        clock.advance(1.0)
    sup._reap_and_adopt()
    assert sup.tallies["crashed"] == 0
    assert sup.tallies["parked"] == 0
    assert sup.policy.parks() == []
    assert sup.tallies["clean_exits"] >= 1


def test_retire_picks_newest_by_supervision_order(harness, queue, clock):
    """Scale-down retires the most recently spawned worker, by seq —
    not by pid, which wraps and misorders adopted orphans."""
    sup, spawned = harness
    for i in range(8):
        queue.enqueue("detect", {"n": i})
    sup.tick()                                   # spawns 4 (ceil 8/2)
    assert len(spawned) == 4
    # Oldest worker wears the numerically HIGHEST pid (wraparound).
    oldest, newest = spawned[0], spawned[-1]
    old_pid, new_pid = oldest.pid, newest.pid
    del sup.workers[old_pid], sup.workers[new_pid]
    oldest.pid, newest.pid = new_pid, old_pid
    from firebird_tpu.fleet.supervisor import _Spawned
    sup.workers[oldest.pid] = _Spawned(oldest.pid, oldest, seq=1)
    sup.workers[newest.pid] = _Spawned(newest.pid, newest, seq=4)
    sup._retire(1)
    assert newest.signals and not oldest.signals


def test_scale_up_counter_requires_a_successful_spawn(tmp_path, queue,
                                                      clock):
    """fleet_scale_up_total counts scale-ups ACTED ON: a tick whose
    every spawn attempt fails must not increment it."""
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)

    def failing_spawn():
        raise OSError("fork: ENOMEM")

    sup = Supervisor(
        cfg, queue,
        policy=ScalePolicy(0, 5, jobs_per_worker=2.0, up_after_sec=0.0,
                           idle_after_sec=10.0, clock=clock,
                           rng=random.Random(3)),
        spawn=failing_spawn, grace_sec=20.0, clock=clock,
        sleep=lambda s: None, proc_start=lambda pid: None)
    queue.enqueue("detect", {"n": 1})
    st = sup.tick()
    assert st["tallies"]["spawned"] == 0
    assert obs_metrics.counter("fleet_scale_up_total").value == 0


def test_pid_alive_treats_eperm_as_alive(monkeypatch):
    """os.kill(pid, 0) raising EPERM means the process EXISTS (another
    user owns it): pruning its registry row would orphan a live worker
    forever."""
    from firebird_tpu.fleet import supervisor as supmod

    def eperm_kill(pid, sig):
        raise PermissionError(1, "Operation not permitted")

    monkeypatch.setattr(supmod.os, "kill", eperm_kill)
    # /proc read of a foreign pid may also fail — still alive.
    assert supmod.pid_alive(999999) is True


def test_supervisor_run_survives_transient_queue_errors(harness, queue,
                                                        clock):
    """One sqlite 'database is locked' burst mid-run must not kill the
    control plane and orphan the fleet: the loop logs, skips the tick,
    and recovers on the next one."""
    import sqlite3
    import threading

    sup, spawned = harness
    queue.enqueue("detect", {"n": 1})
    real_snapshot = queue.scale_snapshot
    fails = {"n": 2}

    def flaky_snapshot(**kw):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise sqlite3.OperationalError("database is locked")
        return real_snapshot(**kw)

    queue.scale_snapshot = flaky_snapshot
    stop = threading.Event()
    ticks = {"n": 0}

    def sleep(sec):
        ticks["n"] += 1
        clock.advance(2.0)
        for p in spawned:                        # workers drain the job
            if p.returncode is None:
                lease = queue.claim("w")
                if lease is not None:
                    queue.ack(lease)
                p.returncode = 0
        if ticks["n"] > 20:
            stop.set()
    sup._sleep = sleep
    summary = sup.run(until_drained=True, stop=stop)
    assert fails["n"] == 0                       # both failures consumed
    assert len(spawned) >= 1                     # fleet still scaled up
    assert summary["queue"]["done"] == 1


def test_until_drained_exits_past_open_stream_jobs(harness, queue, clock):
    """Stream jobs must not gate the supervisor's drain exit: the
    policy provisions no batch capacity for them, so a watcher feeding
    stream jobs would pin `supervise --until-drained` open forever at
    target 0."""
    import signal as sig
    import threading

    sup, spawned = harness
    queue.enqueue("detect", {"n": 1})
    queue.enqueue("stream", {"cx": 0, "cy": 0})  # standing fleet's job
    assert not queue.drained()
    assert not queue.drained(batch_only=True)    # batch work open

    stop = threading.Event()
    ticks = {"n": 0}

    def sleep(sec):
        ticks["n"] += 1
        clock.advance(2.0)
        for p in spawned:                        # drain the BATCH job
            if p.returncode is None:
                lease = queue.claim("w")
                if lease is not None and lease.job_type == "detect":
                    queue.ack(lease)
                if sig.SIGTERM in p.signals:
                    p.returncode = 0
        if ticks["n"] > 30:
            stop.set()                           # would mean: hung
    sup._sleep = sleep
    summary = sup.run(until_drained=True, stop=stop)
    assert not stop.is_set()                     # exited by itself
    assert not summary["wedged"]
    assert queue.drained(batch_only=True)
    assert not queue.drained()                   # stream job still open


def test_pruned_live_worker_reregisters_on_next_beat(tmp_path, queue,
                                                     clock):
    """A supervisor that misreads a live worker's pid as dead prunes
    its row; the worker's next beat must resurrect it (worker_beat
    returns False -> re-register), or it stays invisible to adoption
    and gets double-spawned over forever."""
    from firebird_tpu.fleet.worker import FleetWorker

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    worker = FleetWorker(cfg, queue, kind="batch")
    queue.worker_register(worker.worker_id, os.getpid(), kind="batch",
                          host="h")
    assert queue.worker_beat(worker.worker_id, acked=1) is True
    queue.worker_deregister(worker.worker_id)    # the misread prune
    assert queue.worker_beat(worker.worker_id) is False
    worker._worker_beat()                        # worker's next beat
    (row,) = queue.workers()
    assert row["pid"] == os.getpid()


def test_reregistration_refreshes_started_stamp(queue, clock):
    """worker_id is host:pid — after a reboot a recycled pid collides
    with a crashed worker's durable row, and a stale `started` stamp
    would make the recycled-pid guard prune the LIVE worker."""
    queue.worker_register("h:77", 77, kind="batch", host="h")
    clock.advance(1000.0)                        # host reboots, pid reused
    queue.worker_register("h:77", 77, kind="batch", host="h")
    (row,) = queue.workers()
    assert row["started"] == clock.t             # refreshed, not stale
    assert row["up_sec"] == 0.0


def test_idle_worker_beats_and_recovers_pruned_row(tmp_path, queue):
    """An idle --hold-idle floor worker must keep beating (or it reads
    as dead in `fleet status`) and must re-register if its row was
    pruned while it idled — the prune-recovery path only runs from
    _worker_beat, which the idle loop must therefore reach."""
    import threading

    from firebird_tpu.fleet.worker import FleetWorker

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    rows = {"n": None}
    polls = []

    def nap(sec):
        polls.append(sec)
        if len(polls) == 1:
            # A supervisor misread prunes the idle worker's row.
            queue.worker_deregister(worker.worker_id)
        if len(polls) == 2:
            # The idle branch's beat between poll 1 and 2 must have
            # re-registered the pruned row (run() deregisters on clean
            # exit, so observe mid-flight).
            rows["n"] = len(queue.workers())
            stop.set()

    worker = FleetWorker(cfg, queue, kind="batch", sleep=nap)
    stop = threading.Event()
    worker.run(forever=True, stop=stop)
    assert rows["n"] == 1


def test_supervise_pins_its_own_jax_to_cpu(tmp_path, queue, monkeypatch):
    """The supervisor runs no kernels: it must pin ITS jax platform to
    cpu before ops bring-up, or its topology probe acquires the TPU
    exclusively and every spawned worker crash-loops at bring-up."""
    from click.testing import CliRunner

    from firebird_tpu import cli

    pinned = []
    monkeypatch.setattr(cli, "apply_platform",
                        lambda platform=None: pinned.append(platform))
    env = {"FIREBIRD_STORE_PATH": str(tmp_path / "s.db"),
           "FIREBIRD_FLEET_DB": queue.path,
           "FIREBIRD_OPS_PORT": "0"}
    res = CliRunner().invoke(
        cli.entrypoint,
        ["fleet", "supervise", "--until-drained", "--tick", "0.01"],
        env=env)
    assert res.exit_code == 0, res.output
    assert "cpu" in pinned


def test_until_drained_exits_wedged_through_min_floor(tmp_path, queue,
                                                      clock):
    """A wedged queue under a min_workers floor: the --hold-idle floor
    never self-exits and can claim nothing, so run() must retire it
    and exit wedged instead of spinning forever."""
    import signal as sig
    import threading

    spawned = []

    def spawn():
        p = FakeProc()
        spawned.append(p)
        return p

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    sup = Supervisor(
        cfg, queue,
        policy=ScalePolicy(1, 5, up_after_sec=0.0, idle_after_sec=10.0,
                           clock=clock, rng=random.Random(3)),
        spawn=spawn, grace_sec=20.0, clock=clock,
        proc_start=lambda pid: None)
    # Wedge: a dead upstream with a blocked downstream.
    up = queue.enqueue("detect", {"poison": 1}, max_attempts=1)
    queue.enqueue("product", {"n": 1}, depends_on=[up])
    lease = queue.claim("w0")
    queue.fail(lease, "poison")
    assert queue.wedged()

    stop = threading.Event()
    ticks = {"n": 0}

    def sleep(sec):
        ticks["n"] += 1
        clock.advance(2.0)
        for p in spawned:                        # drain-on-term exit
            if p.returncode is None and sig.SIGTERM in p.signals:
                p.returncode = 0
        if ticks["n"] > 30:
            stop.set()                           # would mean: hung
    sup._sleep = sleep
    summary = sup.run(until_drained=True, stop=stop)
    assert not stop.is_set()                     # exited by itself
    assert summary["wedged"] is True
    assert sup.workers == {}


def test_second_live_supervisor_is_refused(tmp_path, queue, clock,
                                           monkeypatch):
    """Two live supervisors on one queue would adopt each other's
    workers and jointly run ~2x max_workers: a fresh same-host
    heartbeat with a live pid refuses startup; a dead predecessor's
    (SIGKILL) passes."""
    from firebird_tpu.fleet import supervisor as supmod
    from firebird_tpu.obs import jsonlog

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    sup = Supervisor(cfg, queue,
                     policy=ScalePolicy(0, 5, clock=clock),
                     spawn=lambda: None, clock=clock,
                     sleep=lambda s: None, proc_start=lambda pid: None)
    # A LIVE predecessor: fresh beat, live pid (this test process).
    queue.supervisor_heartbeat({"pid": os.getpid() + 0, "host":
                                jsonlog.HOST, "target": 1})
    monkeypatch.setattr(supmod.os, "getpid", lambda: 99999)
    with pytest.raises(RuntimeError, match="another supervisor"):
        sup._refuse_live_predecessor()
    # A DEAD predecessor (SIGKILLed): fresh beat but dead pid — adopts.
    queue.supervisor_heartbeat({"pid": 4194000, "host": jsonlog.HOST,
                                "target": 1})
    sup._refuse_live_predecessor()               # no raise
    # A STALE same-pid-recycling case: beat far in the past — passes.
    queue.supervisor_heartbeat({"pid": os.getpid(), "host": jsonlog.HOST,
                                "target": 1})
    clock.advance(1000.0)
    sup._refuse_live_predecessor()               # no raise


def test_recent_dead_rows_feed_circuit_stale_rows_do_not(tmp_path, queue,
                                                         clock):
    """Registry rows of never-supervised dead workers: a RECENT beat is
    a crash-storm continuation across a supervisor restart (circuit
    food); an hours-stale row (host reboot) prunes silently."""
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 fleet_db=queue.path)
    sup = Supervisor(
        cfg, queue,
        policy=ScalePolicy(0, 5, crash_limit=3, crash_window_sec=60.0,
                           clock=clock, rng=random.Random(3)),
        spawn=lambda: None, clock=clock, sleep=lambda s: None,
        proc_start=lambda pid: None)
    # Three dead rows with fresh beats (a predecessor's crash storm) —
    # pids that cannot be alive.
    for i in range(3):
        queue.worker_register(f"h:{4194100 + i}", 4194100 + i,
                              kind="batch", host=None)
    sup._reap_and_adopt()
    assert sup.tallies["crashed"] == 3
    assert sup.tallies["parked"] == 1            # limit 3 in window
    assert queue.workers() == []                 # rows pruned
    # A stale row: beat far outside the crash window — silent prune.
    queue.worker_register("h:4194200", 4194200, kind="batch", host=None)
    clock.advance(3600.0)
    before = sup.tallies["crashed"]
    sup._reap_and_adopt()
    assert sup.tallies["crashed"] == before
    assert queue.workers() == []
