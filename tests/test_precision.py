"""Mixed-precision envelope (FIREBIRD_MIXED_PRECISION).

The bf16 split-dot gram (pallas_ops._gram_cd_core mixed=True) trades
MXU passes for ~2^-17 relative error in the normal equations — but the
decision plane (break days, curve QA, segment counts, ranks, masks,
procedures) is computed behind the f32 envelope and must be IDENTICAL
to the full-f32 route, with the continuous coef/rmse payload pinned to
``params.MIXED_ULP_BUDGET`` scale-anchored ulps (see the params.py
rationale).  The fuzz golden here seeds lanes whose change score sits
AT the chi2 detection threshold — the exact surface where leaked gram
error would flip a break decision.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from firebird_tpu.ccd import kernel, params, synthetic
from firebird_tpu.ingest.packer import PackedChips

P_TEST = 32
EPS32 = 2.0 ** -23
DECISION_META_COLS = [0, 1, 2, 4, 5]   # sday, eday, bday, curqa, rank


@pytest.fixture(autouse=True, scope="module")
def _precision_env():
    """Mixed only changes arithmetic inside the Pallas fit routes; the
    module baseline is the Pallas fit kernel (test_fuse's precedent)."""
    old = os.environ.get("FIREBIRD_PALLAS")
    os.environ["FIREBIRD_PALLAS"] = "fit"
    yield
    if old is None:
        os.environ.pop("FIREBIRD_PALLAS", None)
    else:
        os.environ["FIREBIRD_PALLAS"] = old


def _threshold_fuzz_pixels(seed=11):
    """Breaks, spikes, and a ladder of marginal steps bracketing the
    detection threshold (standardized score ~ CHANGE_THRESHOLD, where
    ~2^-17 gram error flips the verdict if it escapes the envelope),
    plus starved/cloud/fill lanes."""
    rng = np.random.default_rng(seed)
    t = synthetic.acquisition_dates("1995-01-01", "1997-06-01", 16)
    T = t.shape[0]
    px = []
    for i in range(8):
        Y = synthetic.harmonic_series(t, rng)
        if i % 2 == 0:
            Y[:, T // 2:] += 800.0            # clean break + re-init
        if i % 3 == 0:
            Y[:, rng.integers(0, T)] += 2500  # spike (outlier path)
        px.append((Y, np.full(T, synthetic.QA_CLEAR, np.uint16)))
    for i in range(8):
        Y = synthetic.harmonic_series(t, rng)
        Y[:, T // 2:] += 85.0 + 6.0 * i       # marginal step ladder
        px.append((Y, np.full(T, synthetic.QA_CLEAR, np.uint16)))
    qs = np.full(T, synthetic.QA_CLOUD, np.uint16)
    qs[:: max(T // 5, 1)] = synthetic.QA_CLEAR
    px.append((synthetic.harmonic_series(t, rng), qs))   # init-starved
    while len(px) < P_TEST:
        px.append((np.full((7, T), params.FILL_VALUE, np.float64),
                   np.full(T, synthetic.QA_FILL, np.uint16)))
    order = rng.permutation(P_TEST)
    return t, [px[i] for i in order]


def _pack(t, pixels):
    Ys, qas = zip(*pixels)
    spectra = np.stack([np.asarray(Y, np.int16) for Y in Ys])
    return PackedChips(
        cids=np.stack([np.full(2, 0, np.int64)]),
        dates=t[None].astype(np.int32),
        spectra=spectra.transpose(1, 0, 2)[None],
        qas=np.stack(qas)[None],
        n_obs=np.array([t.shape[0]], np.int32))


def _scaled_ulps(mixed, f32, vector_axis=None):
    """params.MIXED_ULP_BUDGET's metric: |mixed - f32| / (eps32 * scale),
    scale anchored at the coefficient vector's max magnitude (coefs) or
    the element's own (rmse) — never below 1."""
    mixed = np.asarray(mixed, np.float64)
    f32 = np.asarray(f32, np.float64)
    if vector_axis is not None:
        scale = np.maximum(np.abs(f32).max(axis=vector_axis,
                                           keepdims=True), 1.0)
    else:
        scale = np.maximum(np.abs(f32), 1.0)
    return np.abs(mixed - f32) / (EPS32 * scale)


@pytest.mark.slow  # ~45s (two full kernel shapes); `make test` / precision-smoke dispatch the same mixed-vs-f32 comparison every verify run
def test_mixed_decision_identity_and_ulp_budget():
    """The headline contract: mixed vs f32 on the threshold-fuzz chip —
    every decision field byte-identical, coef/rmse inside the pinned
    scaled-ulp budget, seg_mag (a median of residual norms downstream
    of the mixed fit) on a loose envelope."""
    t, px = _threshold_fuzz_pixels()
    pk = _pack(t, px)
    f32 = kernel.detect_packed(pk, dtype=jnp.float32, compact=True,
                               fused=False, mixed=False)
    mx = kernel.detect_packed(pk, dtype=jnp.float32, compact=True,
                              fused=False, mixed=True)
    for f in ("n_segments", "mask", "procedure", "rounds"):
        np.testing.assert_array_equal(np.asarray(getattr(mx, f)),
                                      np.asarray(getattr(f32, f)),
                                      err_msg=f)
    np.testing.assert_array_equal(
        np.asarray(mx.seg_meta)[..., DECISION_META_COLS],
        np.asarray(f32.seg_meta)[..., DECISION_META_COLS])
    budget = params.MIXED_ULP_BUDGET
    coef_u = _scaled_ulps(mx.seg_coef, f32.seg_coef, vector_axis=-1)
    rmse_u = _scaled_ulps(mx.seg_rmse, f32.seg_rmse)
    assert float(coef_u.max()) <= budget, float(coef_u.max())
    assert float(rmse_u.max()) <= budget, float(rmse_u.max())
    # measured on this fixture: ~2.7e-4 max relative (median selection
    # can jump by an inter-element gap, so looser than coef/rmse)
    np.testing.assert_allclose(np.asarray(mx.seg_mag),
                               np.asarray(f32.seg_mag),
                               rtol=1e-2, atol=0.5)


@pytest.mark.slow  # ~13s interpret trace; `make precision-smoke` holds the same mixed-vs-f32 envelope at the full-kernel level every verify run
def test_mixed_lasso_fit_matches_f32_closely():
    """The fit kernel pair at the pallas_ops layer: mixed=True lands
    within the scaled-ulp budget of the f32 kernel on int-valued wire
    spectra (the y hi/lo split is exact; only the gram carries bf16
    error), and the zero pattern of masked coefficients is identical."""
    from firebird_tpu.ccd import harmonic, pallas_ops

    rng = np.random.default_rng(3)
    B, T, P, K = 7, 48, 8, 8
    Yt = jnp.asarray(rng.integers(100, 9000, (B, T, P)), jnp.int16)
    w = jnp.asarray(rng.integers(0, 2, (P, T)), jnp.float32)
    t = np.sort(rng.integers(724000, 725000, T)).astype(np.float64)
    X = jnp.asarray(harmonic.design_matrix(t, float(t[0]), K), jnp.float32)
    cm = jnp.ones((P, K), jnp.float32)
    c_f, r_f = pallas_ops.lasso_fit(Yt, w, X, cm, interpret=True)
    c_m, r_m = pallas_ops.lasso_fit(Yt, w, X, cm, mixed=True,
                                    interpret=True)
    budget = params.MIXED_ULP_BUDGET
    cu = _scaled_ulps(c_m, c_f, vector_axis=-1)
    ru = _scaled_ulps(r_m, r_f)
    assert float(cu.max()) <= budget, float(cu.max())
    assert float(ru.max()) <= budget, float(ru.max())
    # the fit genuinely differs (bf16 gram ran) but masked coefs stay 0
    assert float(np.abs(np.asarray(c_m) - np.asarray(c_f)).max()) > 0


def test_mixed_knob_resolution(monkeypatch):
    """use_mixed_precision reads the registered knob; explicit mixed=
    wins at the dispatch layer regardless of env (the fused/compact
    precedent)."""
    monkeypatch.delenv("FIREBIRD_MIXED_PRECISION", raising=False)
    assert kernel.use_mixed_precision() is False
    monkeypatch.setenv("FIREBIRD_MIXED_PRECISION", "1")
    assert kernel.use_mixed_precision() is True
    monkeypatch.setenv("FIREBIRD_MIXED_PRECISION", "0")
    assert kernel.use_mixed_precision() is False
