"""Test harness configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding is
exercised without TPU hardware (mirrors the driver's dryrun_multichip
validation).  Env must be set before jax is imported anywhere.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent compile cache: the suite's dominant cost is XLA compiles of the
# CCD kernel; caching them on disk makes reruns several times faster.
_cache = os.path.join(os.path.dirname(__file__), os.pardir, ".cache", "jax")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.abspath(_cache))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# The axon sitecustomize registers the TPU platform and pins
# JAX_PLATFORMS=axon before any env var we set can win; override through
# jax.config instead (must happen before first jax use).
import jax

jax.config.update("jax_platforms", "cpu")
# The CCD oracle is float64; enable x64 so the JAX kernel can be tested at
# both precisions.
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def free_port() -> int:
    """An OS-assigned free TCP port (shared by the multihost coordinator
    and the ops-endpoint tests; small bind race accepted)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
