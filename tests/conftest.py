"""Test harness configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding is
exercised without TPU hardware (mirrors the driver's dryrun_multichip
validation).  Env must be set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# The CCD oracle is float64; enable x64 so the JAX kernel can be tested at
# both precisions.
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
