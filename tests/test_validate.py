"""The parity-audit tool: kernel vs oracle on sampled pixels of one chip."""

import json

import numpy as np
from click.testing import CliRunner

from firebird_tpu import cli, validate
from firebird_tpu.ingest import SyntheticSource, pack


def small_packed():
    src = SyntheticSource(seed=3, start="1995-01-01", end="1998-01-01")
    chip = src.chip(100, 200)
    p = pack([chip], bucket=32)
    # slim the pixel axis so the audit stays fast
    from firebird_tpu.ingest.packer import PackedChips

    return PackedChips(cids=p.cids, dates=p.dates,
                       spectra=p.spectra[:, :, :256, :],
                       qas=p.qas[:, :256, :], n_obs=p.n_obs)


def test_validate_chip_agrees_structurally():
    rep = validate.validate_chip(small_packed(), n_pixels=24, dtype="float64")
    assert rep["structural_agreement"], rep["mismatches"]
    assert rep["break_day_agreement"] == 1.0
    assert rep["pixels_audited"] == 24
    assert not any(rep["mismatches"].values())
    # float64 vs float64: numeric errors bounded by the CD-amplified
    # summation-order roundoff measured in the fuzz sweep (~1e-4 rel)
    assert rep["numeric_max_rel_err"]["coefficients"] < 1e-3
    assert rep["change_probability_max_abs_err"] < 1e-6
    assert rep["band_segments_checked"] > 0


def test_validate_detects_divergence(monkeypatch):
    """A corrupted kernel result must show up as structural mismatch."""
    p = small_packed()
    real = validate.kernel.detect_packed

    def corrupt(packed, dtype):
        seg = real(packed, dtype=dtype)
        bad = np.asarray(seg.n_segments).copy()
        bad[:, ::2] += 1          # claim an extra segment on half the pixels
        return validate.kernel.ChipSegments(
            n_segments=bad,
            seg_meta=seg.seg_meta, seg_rmse=seg.seg_rmse,
            seg_mag=seg.seg_mag, seg_coef=seg.seg_coef, mask=seg.mask,
            procedure=seg.procedure, rounds=seg.rounds, vario=seg.vario)

    monkeypatch.setattr(validate.kernel, "detect_packed",
                        lambda packed, dtype: corrupt(packed, dtype))
    rep = validate.validate_chip(p, n_pixels=16, dtype="float64")
    assert not rep["structural_agreement"]
    assert rep["mismatches"]["n_models"] > 0


def test_validate_chip_sentinel2():
    """The audit is sensor-generic: a 12-band S2 chip replays through the
    sensor-generic oracle, not the Landsat keyword API."""
    from firebird_tpu.ccd.sensor import SENTINEL2
    from test_fuzz_parity import SPECIALS, _dates, _fuzz_pixel, _pack_pixels

    rng = np.random.default_rng(4)
    t = _dates("2019-01-01", "2021-01-01", 10, 0.1, 0.0, rng)
    pixels = [_fuzz_pixel(t, rng, special=SPECIALS.get(i), sensor=SENTINEL2)
              for i in range(16)]
    p = _pack_pixels(t, [Y for Y, _ in pixels], [q for _, q in pixels],
                     bucket=32, sensor=SENTINEL2)
    rep = validate.validate_chip(p, n_pixels=12, dtype="float64")
    assert rep["structural_agreement"], rep["mismatches"]
    assert rep["break_day_agreement"] == 1.0


def test_validate_rejects_single_coordinate(monkeypatch):
    monkeypatch.setenv("FIREBIRD_SOURCE", "synthetic")
    try:
        validate.validate(x=542000.0)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_cli_validate_synthetic(monkeypatch):
    monkeypatch.setenv("FIREBIRD_SOURCE", "synthetic")
    res = CliRunner().invoke(cli.entrypoint, [
        "validate", "-n", "8", "--dtype", "float64",
        "-a", "1995-01-01/1997-06-01"])
    assert res.exit_code == 0, res.output
    rep = json.loads(res.output[res.output.index("{"):])
    assert rep["structural_agreement"] is True
    assert rep["pixels_audited"] == 8
