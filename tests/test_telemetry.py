"""Fleet telemetry plane: spool ring, wire-format trace propagation,
the collector's merged trace + critical-path attribution, and the
snapshot-rebuilt Prometheus exposition (docs/OBSERVABILITY.md "Fleet
telemetry plane").  The cross-PROCESS end-to-end drill — a live fleet
with a SIGKILLed worker — is `make telemetry-smoke`
(tools/telemetry_smoke.py); these tests pin the unit contracts the
smoke builds on.
"""

import json
import os

import pytest

from firebird_tpu.alerts.log import AlertLog
from firebird_tpu.fleet.queue import FleetQueue
from firebird_tpu.obs import collect as obs_collect
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import report as obs_report
from firebird_tpu.obs import spool as obs_spool
from firebird_tpu.obs import tracing


@pytest.fixture
def fresh_metrics():
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


@pytest.fixture
def sink_guard():
    """Every test that installs the spool span sink must leave the
    process clean — a leaked sink would spool every later test's spans."""
    yield
    tracing.set_spool(None)
    obs_spool.disarm()


# ---------------------------------------------------------------------------
# Wire format: the trace id as it crosses processes
# ---------------------------------------------------------------------------

def test_wire_roundtrip():
    ctx = tracing.TraceContext("scene/LC08_2020-01-01/ab12cd34",
                               run_id="r1")
    wire = tracing.to_wire(ctx)
    assert wire == "scene/LC08_2020-01-01/ab12cd34"
    back = tracing.from_wire(wire, run_id="r2")
    assert back is not None and back.batch_id == wire
    assert back.run_id == "r2"
    assert tracing.to_wire(None) is None


def test_from_wire_rejects_malformed():
    # Queue payloads and HTTP headers are untrusted: anything outside
    # WIRE_RE must be refused (the caller then mints its own context).
    for bad in (None, "", "has space", "semi;colon", "x" * 161,
                42, {"trace": "scene/x"}, b"scene/x", "new\nline"):
        assert tracing.from_wire(bad) is None, bad
    for ok in ("scene/LC08/1a", "req-0f3c", "run.id:7/b3", "a",
               "x" * 160):
        assert tracing.from_wire(ok) is not None, ok


# ---------------------------------------------------------------------------
# The spool: bounded ring, crash recovery, zero-cost disarm
# ---------------------------------------------------------------------------

def test_spool_ring_is_bounded(tmp_path):
    sp = obs_spool.TelemetrySpool(str(tmp_path), "worker",
                                  events_per_segment=5, segments=2,
                                  snapshot_sec=1e9)
    for i in range(23):
        sp.mark("tick", trace=f"t/{i}", i=i)
    sp.close()   # writes the final snapshot line
    segs = sorted(p.name for p in tmp_path.iterdir())
    assert segs == [f"spool.worker.{os.getpid()}.{s}.jsonl"
                    for s in (0, 1)]
    events = obs_collect.read_events(str(tmp_path))
    marks = [e for e in events if e["kind"] == "mark"]
    # the ring kept only the newest <= 2 * 5 events; the oldest rolled off
    assert 0 < len(marks) <= 10
    assert max(e["attrs"]["i"] for e in marks) == 22
    # every surviving event is attributed from its segment header
    assert all(e["role"] == "worker" and e["pid"] == os.getpid()
               for e in events)


def test_collector_skips_torn_tail_line(tmp_path):
    sp = obs_spool.TelemetrySpool(str(tmp_path), "worker",
                                  events_per_segment=100, segments=2,
                                  snapshot_sec=1e9)
    sp.mark("whole", trace="t/1")
    sp.close()
    path = sp.segment_path(0)
    with open(path, "a") as f:
        f.write('{"kind":"mark","name":"torn","t":12')   # SIGKILL mid-write
    events = obs_collect.read_events(str(tmp_path))
    names = [e["name"] for e in events if e["kind"] == "mark"]
    assert names == ["whole"]           # torn line skipped, not fatal


def test_spool_captures_spans_with_trace(tmp_path, sink_guard):
    sp = obs_spool.TelemetrySpool(str(tmp_path), "worker",
                                  snapshot_sec=1e9)
    tracing.set_spool(sp)
    with tracing.activate(tracing.TraceContext("scene/S1/aa")):
        with tracing.span("fetch", chip=(1, 2)):
            pass
    with tracing.span("fetch"):         # outside any context: no trace
        pass
    tracing.set_spool(None)
    sp.close()
    spans = [e for e in obs_collect.read_events(str(tmp_path))
             if e["kind"] == "span"]
    assert [s["trace"] for s in spans] == ["scene/S1/aa", None]
    assert all(s["name"] == "fetch" and s["dur"] >= 0 for s in spans)


def test_arm_disarmed_by_knob_and_memory_backend(tmp_path, sink_guard):
    from firebird_tpu.config import Config

    base = {"FIREBIRD_STORE_BACKEND": "sqlite",
            "FIREBIRD_STORE_PATH": str(tmp_path / "store" / "f.db")}
    cfg = Config.from_env(env=dict(base, FIREBIRD_TELEMETRY="0"))
    assert obs_spool.arm(cfg, "worker") is None
    assert obs_spool.active() is None
    obs_spool.mark("noop", trace="t/1")          # must not throw
    assert tracing.span("fetch") is tracing._NULL_SPAN   # no-op gate holds
    assert not (tmp_path / "store" / "telemetry").exists()
    # the memory backend has no cross-process "next to": spool disabled
    mcfg = Config.from_env(env={"FIREBIRD_STORE_BACKEND": "memory"})
    assert obs_spool.spool_dir(mcfg) is None
    assert obs_spool.arm(mcfg, "worker") is None


def test_arm_derives_dir_next_to_store(tmp_path, sink_guard):
    from firebird_tpu.config import Config

    cfg = Config.from_env(env={
        "FIREBIRD_STORE_BACKEND": "sqlite",
        "FIREBIRD_STORE_PATH": str(tmp_path / "store" / "f.db"),
        "FIREBIRD_TELEMETRY_SNAPSHOT_SEC": "1e9"})
    sp = obs_spool.arm(cfg, "watcher", "run-1")
    assert sp is not None
    assert obs_spool.arm(cfg, "watcher") is sp   # idempotent
    obs_spool.mark("scene_enqueued", trace="scene/S/1", jobs=2)
    obs_spool.disarm()
    d = tmp_path / "store" / "telemetry"
    events = obs_collect.read_events(str(d))
    marks = [e for e in events if e["kind"] == "mark"]
    assert marks and marks[0]["run_id"] == "run-1"


# ---------------------------------------------------------------------------
# Collector: merged Perfetto trace + critical-path attribution
# ---------------------------------------------------------------------------

def _write_segment(directory, role, pid, lines):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"spool.{role}.{pid}.0.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "header", "role": role, "pid": pid,
                            "run_id": f"run-{role}", "segment": 0,
                            "t": 0.0}) + "\n")
        for doc in lines:
            f.write(json.dumps(doc) + "\n")


def _fleet_spool(directory):
    """A hand-built three-process spool for one scene trace: the joints
    and spans of publish(t=1000) -> append(t=1002) -> deliver(t=1002.5)."""
    tr = "scene/LC08_X/aa11"
    _write_segment(directory, "watcher", 11, [
        {"kind": "mark", "name": "scene_enqueued", "t": 1000.5,
         "trace": tr, "tid": 1,
         "attrs": {"scene": "LC08_X", "jobs": 1, "published": 1000.0}}])
    _write_segment(directory, "worker", 12, [
        {"kind": "mark", "name": "job_claimed", "t": 1001.0, "trace": tr,
         "tid": 2, "attrs": {"job": 7}},
        {"kind": "span", "name": "fetch", "t0": 1001.1, "dur": 0.2,
         "trace": tr, "tid": 2, "thread": "MainThread"},
        {"kind": "span", "name": "step", "t0": 1001.4, "dur": 0.3,
         "trace": tr, "tid": 2, "thread": "MainThread"},
        {"kind": "span", "name": "alert", "t0": 1001.8, "dur": 0.1,
         "trace": tr, "tid": 2, "thread": "MainThread"},
        {"kind": "mark", "name": "alert_appended", "t": 1002.0,
         "trace": tr, "tid": 2,
         "attrs": {"chip": [1, 2], "alerts": 5, "deduped": 0,
                   "published": 1000.0, "acq_to_alert": 2.0}},
        {"kind": "mark", "name": "job_acked", "t": 1002.1, "trace": tr,
         "tid": 2, "attrs": {"job": 7}}])
    _write_segment(directory, "deliverer", 13, [
        {"kind": "span", "name": "deliver", "t0": 1002.3, "dur": 0.2,
         "trace": tr, "tid": 3, "thread": "MainThread"},
        {"kind": "mark", "name": "alert_delivered", "t": 1002.5,
         "trace": tr, "tid": 3, "attrs": {"subscriber": 1, "cursor": 5}}])
    return tr


def test_collector_merges_processes_into_valid_trace(tmp_path):
    tr = _fleet_spool(str(tmp_path))
    doc = obs_collect.collect(str(tmp_path))
    obs_report.validate_trace(doc["trace"])      # Perfetto-loadable
    assert [(p["role"], p["pid"]) for p in doc["processes"]] == \
        [("deliverer", 13), ("watcher", 11), ("worker", 12)]
    evs = doc["trace"]["traceEvents"]
    # one process track per pid, named "<role> <pid>"
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"watcher 11", "worker 12", "deliverer 13"}
    # every span/instant carries the scene's trace id in args — the one
    # filterable id across all three OS processes
    tagged = [e for e in evs if e.get("args", {}).get("trace") == tr]
    pids = {e["pid"] for e in tagged}
    assert pids == {11, 12, 13}
    # instants use the Perfetto-required scope field
    assert all(e.get("s") == "p" for e in evs if e["ph"] == "i")


def test_critical_path_stages_sum_exactly(tmp_path):
    tr = _fleet_spool(str(tmp_path))
    paths = obs_collect.critical_paths(
        obs_collect.read_events(str(tmp_path)))
    assert len(paths) == 1
    cp = paths[0]
    assert cp["trace"] == tr and cp["alerts"] == 5
    assert set(cp["stages"]) == set(obs_collect.CRITICAL_PATH_STAGES)
    s = cp["stages"]
    assert s["watch_lag"] == pytest.approx(0.5)
    assert s["queue_wait"] == pytest.approx(0.5)
    assert s["fetch"] == pytest.approx(0.2)
    assert s["step"] == pytest.approx(0.3)
    assert s["append"] == pytest.approx(0.1)
    # `other` is the explicit residual, so the stages sum EXACTLY
    assert sum(s.values()) == pytest.approx(cp["total"], abs=1e-6)
    assert cp["total"] == pytest.approx(2.0)
    # the measured histogram observation rides on the mark
    assert cp["measured_acq_to_alert"] == pytest.approx(2.0)
    assert cp["delivery"] == pytest.approx(0.5)
    assert cp["processes"] == ["deliverer:13", "watcher:11", "worker:12"]


def test_critical_path_needs_an_append(tmp_path):
    # a trace that never reached a durable append yields no breakdown
    _write_segment(str(tmp_path), "watcher", 11, [
        {"kind": "mark", "name": "scene_enqueued", "t": 1.0,
         "trace": "scene/never/1", "tid": 1,
         "attrs": {"published": 0.5}}])
    assert obs_collect.critical_paths(
        obs_collect.read_events(str(tmp_path))) == []


# ---------------------------------------------------------------------------
# Metric snapshots: exposition rebuild + fleet percentile re-derivation
# ---------------------------------------------------------------------------

def test_prometheus_rebuilt_from_spool_snapshot(tmp_path, fresh_metrics,
                                                sink_guard):
    obs_metrics.counter("fetch_retries").inc(3)
    obs_metrics.gauge("store_queue_depth").set(2)
    h = obs_metrics.histogram("pipeline_fetch_seconds")
    for v in (0.01, 0.2, 1.5):
        h.observe(v)
    sp = obs_spool.TelemetrySpool(str(tmp_path), "worker",
                                  snapshot_sec=1e9)
    sp.close()                                    # close() snapshots
    snaps = obs_collect.latest_snapshots(
        obs_collect.read_events(str(tmp_path)))
    (snap,) = snaps.values()
    text = obs_metrics.prometheus_from_snapshot(snap["metrics"])
    for line in text.splitlines():
        assert obs_metrics.PROM_LINE_RE.match(line), line
    # catalog help + shared naming rules: the rebuilt exposition IS the
    # scrape the live process would have served
    assert text == obs_metrics.get_registry().prometheus()
    assert 'firebird_pipeline_fetch_seconds_bucket{le="+Inf"} 3' in text


def test_fleet_merge_rederives_percentiles(fresh_metrics):
    # two "processes": disjoint observation sets, same fixed buckets
    a_obs = [0.01, 0.02, 0.05, 0.1]
    b_obs = [0.5, 1.0, 2.0, 5.0, 9.0]
    h = obs_metrics.histogram("pipeline_drain_seconds")
    for v in a_obs:
        h.observe(v)
    obs_metrics.gauge("stream_chips").set(3)
    obs_metrics.gauge("store_queue_depth").set(1)
    snap_a = obs_metrics.get_registry().snapshot()
    obs_metrics.reset_registry()
    h = obs_metrics.histogram("pipeline_drain_seconds")
    for v in b_obs:
        h.observe(v)
    obs_metrics.gauge("stream_chips").set(4)
    obs_metrics.gauge("store_queue_depth").set(5)
    snap_b = obs_metrics.get_registry().snapshot()
    merged = obs_collect.merge_snapshots({
        "worker:1": {"t": 1.0, "metrics": snap_a},
        "worker:2": {"t": 2.0, "metrics": snap_b}})
    mh = merged["histograms"]["pipeline_drain_seconds"]
    assert mh["count"] == len(a_obs) + len(b_obs)
    assert mh["sum"] == pytest.approx(sum(a_obs) + sum(b_obs))
    # percentiles re-derive from the ADDED bucket counts: identical to a
    # single registry that observed every value itself
    obs_metrics.reset_registry()
    h = obs_metrics.histogram("pipeline_drain_seconds")
    for v in a_obs + b_obs:
        h.observe(v)
    ref = obs_metrics.histogram("pipeline_drain_seconds").snapshot()
    for q in ("p50", "p95", "p99"):
        assert mh[q] == pytest.approx(ref[q]), q
    assert mh["bucket_counts"] == ref["bucket_counts"]
    # gauges merge per the declared policy: stream_* sums, depths max
    assert merged["gauges"]["stream_chips"] == 7
    assert merged["gauges"]["store_queue_depth"] == 5


# ---------------------------------------------------------------------------
# Propagation surfaces: queue payloads and alert rows
# ---------------------------------------------------------------------------

def test_queue_payload_trace_survives_redelivery(tmp_path):
    clock = [1000.0]
    q = FleetQueue(str(tmp_path / "fleet.db"), lease_sec=30.0,
                   clock=lambda: clock[0])
    tr = "scene/LC08_X/aa11"
    q.enqueue("stream", {"cx": 1, "cy": 2, tracing.TRACE_KEY: tr})
    lease = q.claim("w1")
    assert lease.payload[tracing.TRACE_KEY] == tr
    clock[0] += 31.0                 # the SIGKILLed worker's lease lapses
    lease2 = q.claim("w2")           # re-delivery, fresh fence
    assert lease2.job_id == lease.job_id and lease2.fence != lease.fence
    assert lease2.payload[tracing.TRACE_KEY] == tr   # verbatim round-trip
    q.ack(lease2)
    q.close()


def test_alert_rows_carry_trace_and_migrate(tmp_path):
    import sqlite3

    path = str(tmp_path / "alerts.db")
    log = AlertLog(path)
    tr = "scene/LC08_X/aa11"
    rec = {"cx": 1, "cy": 2, "px": 10, "py": 20, "break_day": 730000.0}
    log.append([rec], run_id="r1", trace=tr)
    # a record carrying its OWN trace wins over the batch default
    log.append([dict(rec, px=11, trace="scene/other/bb22")], trace=tr)
    rows = log.since(0)
    assert [r["trace"] for r in rows] == [tr, "scene/other/bb22"]
    log.close()
    # pre-telemetry schema (no trace column) migrates on open
    old = str(tmp_path / "old.db")
    con = sqlite3.connect(old)
    con.execute("CREATE TABLE alerts ("
                " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                " cx INTEGER NOT NULL, cy INTEGER NOT NULL,"
                " px INTEGER NOT NULL, py INTEGER NOT NULL,"
                " break_day REAL NOT NULL,"
                " score REAL, magnitude REAL,"
                " run_id TEXT, detected_at TEXT,"
                " UNIQUE (px, py, break_day))")
    con.execute("INSERT INTO alerts (cx, cy, px, py, break_day) "
                "VALUES (1, 2, 3, 4, 729000.0)")
    con.commit()
    con.close()
    mig = AlertLog(old)
    rows = mig.since(0)
    assert [r["trace"] for r in rows] == [None]      # legacy row readable
    mig.append([dict(rec, px=12)], trace=tr)
    assert mig.since(0)[-1]["trace"] == tr           # new rows stamped
    mig.close()
