"""Streaming driver end-to-end: bootstrap -> checkpoint -> apply -> publish.

A controlled source whose archive contains a step change *after* the
bootstrap window proves the full operational loop: the first stream() run
bootstraps batch detection and seeds state; the second applies only the
new acquisitions, absorbs the pre-change ones (eday advances), confirms
the break (chprob 1.0 published, pixels flagged for the cold-path batch
rerun); a third run with the same range is a no-op.
"""

import json

import numpy as np
import pytest

from firebird_tpu.ccd import params, synthetic
from firebird_tpu.config import Config
from firebird_tpu.driver import stream as sdrv
from firebird_tpu.ingest.packer import ChipData
from firebird_tpu.store import open_store
from firebird_tpu.utils import dates as dt


def _state_chips(cfg):
    """Chip ids with a stream checkpoint, whatever the configured
    statestore layout (packed tile files by default)."""
    from firebird_tpu.streamops import open_statestore

    st = open_statestore(cfg)
    try:
        return st.chips()
    finally:
        st.close()


class StepSource:
    """One chip whose every pixel steps +800 on all bands at CHANGE_DATE."""

    CHANGE_DATE = "1999-06-01"

    def __init__(self):
        rng = np.random.default_rng(7)
        self.t = synthetic.acquisition_dates("1995-01-01", "2001-01-01", 16)
        T = self.t.shape[0]
        base = synthetic.harmonic_series(self.t, rng)            # [7, T]
        noise = rng.normal(0.0, 10.0, (7, T, 100, 100))
        spectra = base[:, :, None, None] + noise
        spectra[:, self.t >= dt.to_ordinal(self.CHANGE_DATE)] += 800.0
        self.spectra = np.clip(spectra, -32768, 32767).astype(np.int16)
        self.qas = np.full((T, 100, 100), synthetic.QA_CLEAR, np.uint16)

    def chip(self, x, y, acquired):
        lo, hi = (dt.to_ordinal(s) for s in acquired.split("/"))
        m = (self.t >= lo) & (self.t <= hi)
        return ChipData(cx=int(x), cy=int(y), dates=self.t[m],
                        spectra=self.spectra[:, m], qas=self.qas[m])


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stream")
    cfg = Config(store_backend="sqlite", store_path=str(tmp / "s.db"),
                 stream_dir=str(tmp / "state"), source_backend="synthetic")
    src = StepSource()
    mk_store = lambda: open_store(cfg.store_backend, cfg.store_path,
                                  cfg.keyspace())
    s1 = sdrv.stream(100, 200, acquired="1995-01-01/1998-12-31", number=1,
                     cfg=cfg, source=src, store=mk_store())
    s2 = sdrv.stream(100, 200, acquired="1995-01-01/2000-12-31", number=1,
                     cfg=cfg, source=src, store=mk_store())
    s3 = sdrv.stream(100, 200, acquired="1995-01-01/2000-12-31", number=1,
                     cfg=cfg, source=src, store=mk_store())
    return cfg, s1, s2, s3, mk_store()


@pytest.mark.slow  # the shared `runs` fixture is ~90s (three full stream() passes over a 100x100 chip); streamfleet-smoke drives the same bootstrap->update->publish loop end-to-end in `make test`
def test_bootstrap_then_update_then_noop(runs):
    cfg, s1, s2, s3, _ = runs
    assert s1["bootstrapped"] == 1 and s1["updated"] == 0
    assert s2["bootstrapped"] == 0 and s2["updated"] == 1
    # ~46 sixteen-day acquisitions between 1999-01 and 2000-12
    assert s2["obs_applied"] >= 40
    # the step change broke every standard pixel
    assert s2["pixels_need_batch"] >= 9000
    # same range again: nothing new, flags persist in the checkpoint
    assert s3["updated"] == 0 and s3["obs_applied"] == 0
    assert s3["pixels_need_batch"] == s2["pixels_need_batch"]
    assert _state_chips(cfg)


@pytest.mark.slow  # shares the ~90s `runs` fixture; streamfleet-smoke asserts published rows from a drained stream in `make test`
def test_published_rows_reflect_stream(runs):
    _, _, _, _, store = runs
    seg = store.read("segment")
    chprob = np.array([v if v is not None else np.nan
                       for v in seg["chprob"]], float)
    eday = np.asarray(seg["eday"])
    bday = np.asarray(seg["bday"])
    # stream-confirmed breaks published: chprob 1.0 with a 1999 break day
    broke = chprob == 1.0
    assert broke.any()
    years = {d[:4] for d in bday[broke]}
    assert years == {"1999"}
    # pre-change 1999 acquisitions were absorbed: eday advanced past the
    # bootstrap horizon (1998-12-31) on the published tails
    assert (eday[broke] >= "1999-01-01").all()
    # the break is dated at the first exceeding acquisition, not later
    assert (bday[broke] <= "1999-07-01").all()


@pytest.mark.slow  # shares the ~90s `runs` fixture; alert-smoke runs the alert-emission drill end-to-end in `make test`
def test_alerts_emitted_exactly_once_and_repair_scheduled(runs):
    """The alerting loop over the same runs: the update pass that
    confirmed the step change must emit one durable alert per broken
    pixel (docs/ALERTS.md), the no-op rerun must emit nothing (and
    dedup nothing — no delta means no re-emission), and the needs_batch
    debt must be exactly ONE open repair job on the fleet queue."""
    from firebird_tpu.alerts import AlertLog, alert_db_path
    from firebird_tpu.fleet import FleetQueue, queue_path

    cfg, s1, s2, s3, _ = runs
    assert s1["alerts_emitted"] == 0            # bootstrap never alerts
    assert s2["alerts_emitted"] >= 9000
    assert s2["alerts_deduped"] == 0
    assert s3["alerts_emitted"] == 0 and s3["alerts_deduped"] == 0
    al = AlertLog(alert_db_path(cfg))
    try:
        assert al.count() == s2["alerts_emitted"]
        recs = al.since(0, limit=10)
    finally:
        al.close()
    from firebird_tpu.ingest.packer import CHIP_SIDE, PIXEL_SIZE_M

    side_m = CHIP_SIDE * PIXEL_SIZE_M
    for r in recs:
        # dated like the published bday rows: the first exceeding 1999
        # acquisition, scored at confirmation, with a live magnitude,
        # and pixel coords landing inside the record's own chip
        assert r["break_date"].startswith("1999")
        assert r["score"] == 1.0
        assert r["magnitude"] > 1.0
        assert r["cx"] <= r["px"] < r["cx"] + side_m
        assert r["cy"] - side_m < r["py"] <= r["cy"]
    # one open repair job for the one broken chip — scheduled by s2,
    # skipped (not duplicated) by s3's re-roll of the same debt
    assert s2["repair_jobs_enqueued"] == 1
    assert s3["repair_jobs_enqueued"] == 0
    q = FleetQueue(queue_path(cfg))
    try:
        assert q.counts()["pending"] == 1
        (job_cid,) = q.open_jobs("repair")
        job = q.job(q.open_jobs("repair")[job_cid])
        assert job["payload"]["pixels"] == s3["pixels_need_batch"]
    finally:
        q.close()


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from firebird_tpu.ccd import incremental

    P = 5
    st = incremental.StreamState(
        coefs=jnp.ones((P, 7, 8)), rmse=jnp.ones((P, 7)),
        vario=jnp.ones((P, 7)), nobs=jnp.full(P, 3, jnp.int32),
        n_exceed=jnp.zeros(P, jnp.int32), end_day=jnp.full(P, 7.0),
        exceed_day0=jnp.zeros(P), break_day=jnp.zeros(P),
        active=jnp.ones(P, bool))
    side = dict(sday=np.ones(P), curqa=np.full(P, 24, np.int64),
                anchor=np.float64(5.0), horizon=np.float64(7.0))
    path = str(tmp_path / "st.npz")
    sdrv.save_state(path, st, side)
    st2, side2 = sdrv.load_state(path)
    for f in sdrv._STATE_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(st, f)),
                                      np.asarray(getattr(st2, f)))
    assert float(side2["horizon"]) == 7.0 and int(side2["curqa"][0]) == 24


@pytest.mark.slow  # ~52s (multi-chip bootstrap through the 8-device virtual mesh, twice); streamfleet-smoke drains a sharded multi-chip stream end-to-end in `make test`
def test_sharded_bootstrap_multi_chip(tmp_path):
    """VERDICT round-1 weak #6: the stream driver composes with the batch
    driver's device sharding — a multi-chip bootstrap batch runs through
    detect_batch's local-device mesh (8 virtual devices in this suite),
    then the per-chip hot path updates every chip."""
    import jax

    assert jax.local_device_count() >= 2    # conftest virtual mesh
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 stream_dir=str(tmp_path / "state"),
                 source_backend="synthetic", chips_per_batch=2)
    src = StepSource()
    mk = lambda: open_store(cfg.store_backend, cfg.store_path,
                            cfg.keyspace())
    s1 = sdrv.stream(100, 200, acquired="1995-01-01/1998-12-31", number=2,
                     cfg=cfg, source=src, store=mk())
    assert s1["bootstrapped"] == 2 and s1["updated"] == 0
    assert len(_state_chips(cfg)) == 2
    # both chips' batch rows landed under their own chip keys
    seg = mk().read("segment")
    assert len({(x, y) for x, y in zip(seg["cx"], seg["cy"])}) == 2
    # second run: per-chip incremental updates for every bootstrapped chip
    s2 = sdrv.stream(100, 200, acquired="1995-01-01/2000-12-31", number=2,
                     cfg=cfg, source=src, store=mk())
    assert s2["bootstrapped"] == 0 and s2["updated"] == 2
    assert s2["obs_applied"] >= 80          # ~46 new acquisitions per chip


@pytest.mark.slow
def test_stream_quarantine_branch_and_drain(tmp_path):
    """The stream driver's per-chip isolation (the branch chaos never
    exercised): a poisoned chip is dead-lettered to quarantine.json
    without failing the run, the other chip bootstraps normally, and the
    next stream run (poison cleared) drains the quarantine."""
    from firebird_tpu import grid
    from firebird_tpu.driver import quarantine as qlib
    from firebird_tpu.utils.fn import take

    cids = list(take(2, grid.chips(grid.tile(x=100, y=200))))
    poisoned = tuple(int(v) for v in cids[0])
    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "s.db"),
                 stream_dir=str(tmp_path / "state"),
                 source_backend="synthetic", chips_per_batch=1,
                 fetch_retries=0,
                 faults=f"ingest:chip={poisoned[0]}:{poisoned[1]}")
    src = StepSource()
    mk = lambda: open_store(cfg.store_backend, cfg.store_path,
                            cfg.keyspace())
    s1 = sdrv.stream(100, 200, acquired="1995-01-01/1998-12-31", number=2,
                     cfg=cfg, source=src, store=mk())
    assert s1["bootstrapped"] == 1 and s1["quarantined"] == 1
    qpath = qlib.quarantine_path(cfg)
    doc = json.load(open(qpath))
    assert doc["chips"][f"{poisoned[0]},{poisoned[1]}"]["stage"] == "stream"
    assert len(_state_chips(cfg)) == 1

    # poison cleared: the missing chip bootstraps, the landed one
    # updates, and the dead letter drains
    healed = Config(**{**cfg.__dict__, "faults": ""})
    s2 = sdrv.stream(100, 200, acquired="1995-01-01/1998-12-31", number=2,
                     cfg=healed, source=src, store=mk())
    assert s2["bootstrapped"] == 1 and s2["quarantined"] == 0
    assert len(qlib.Quarantine.load(qpath)) == 0
    assert len(_state_chips(cfg)) == 2
