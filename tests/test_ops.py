"""Live ops surface: HTTP endpoints, stall watchdog, run-correlated JSON
logs, and multi-host report aggregation (obs/server.py, obs/watchdog.py,
obs/jsonlog.py, obs.report merge)."""

import json
import logging
import threading
import time
import urllib.error
import urllib.request

import pytest

from firebird_tpu.config import Config
from firebird_tpu.obs import jsonlog
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import report as obs_report
from firebird_tpu.obs import server as obs_server
from firebird_tpu.obs import tracing
from firebird_tpu.obs.metrics import PROM_LINE_RE as PROM_LINE
from firebird_tpu.obs.watchdog import Watchdog


def _get(port, path):
    """(status, body bytes) against the local ops server."""
    try:
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5)
        return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture
def clean_status():
    yield
    obs_server.clear_status()
    jsonlog.clear_run_context()


# ---------------------------------------------------------------------------
# Ops server endpoints
# ---------------------------------------------------------------------------

def test_ops_disabled_by_default():
    """No port is ever bound unless explicitly asked for: the config
    default is off and both drivers gate on it (cfg.ops_port > 0)."""
    from firebird_tpu.driver import core

    assert Config().ops_port == 0
    assert Config.from_env(env={}).ops_port == 0
    counters = obs_metrics.Counters()
    try:
        _, srv, wd = core.start_ops(
            Config(), "rid", "test", chips_total=1, counters=counters,
            run_block={})
        assert srv is None and wd is None
    finally:
        core.stop_ops(None, None)
    with pytest.raises(ValueError):
        Config(ops_port=99999)


def test_ops_endpoints_roundtrip(clean_status):
    counters = obs_metrics.Counters()
    counters.add("chips", 3)
    status = obs_server.RunStatus(
        "run-1", "changedetection", chips_total=8, counters=counters,
        run={"kind": "changedetection", "run_id": "run-1"})
    srv = obs_server.start_ops_server(0, status, host="127.0.0.1")
    try:
        code, body = _get(srv.port, "/healthz")
        assert (code, body) == (200, b"ok\n")

        # not ready until the first batch dispatches
        code, _ = _get(srv.port, "/readyz")
        assert code == 503
        status.batch_dispatched()
        code, _ = _get(srv.port, "/readyz")
        assert code == 200

        status.set_stage("dispatch")
        status.batch_done(3)
        code, body = _get(srv.port, "/progress")
        assert code == 200
        prog = json.loads(body)
        assert prog["run_id"] == "run-1"
        assert prog["stage"] == "dispatch"
        assert prog["chips_done"] == 3 and prog["chips_total"] == 8
        assert prog["batches_dispatched"] == 1
        assert prog["batches_done"] == 1
        assert prog["ready"] and prog["healthy"]
        assert prog["counters"]["chips"] == 3

        code, body = _get(srv.port, "/metrics")
        assert code == 200
        for ln in body.decode().splitlines():
            assert PROM_LINE.match(ln), ln

        code, body = _get(srv.port, "/report")
        assert code == 200
        rep = json.loads(body)
        obs_report.validate_report(rep)
        assert rep["run"]["run_id"] == "run-1"
        assert rep["run_counters"]["chips"] == 3

        code, body = _get(srv.port, "/nope")
        assert code == 404 and b"unknown path" in body
    finally:
        srv.close()


def test_ops_server_serves_module_status(clean_status):
    """A server started without an explicit status falls back to the
    process-global slot the drivers publish into."""
    srv = obs_server.start_ops_server(0, host="127.0.0.1")
    try:
        code, _ = _get(srv.port, "/progress")
        assert code == 503                       # no run registered
        obs_server.set_status(obs_server.RunStatus("run-2", "stream"))
        obs_server.set_stage("update")
        code, body = _get(srv.port, "/progress")
        assert code == 200
        assert json.loads(body)["stage"] == "update"
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

def test_watchdog_stall_and_recovery(clean_status):
    obs_metrics.reset_registry()
    clock = [0.0]
    wd = Watchdog(stall_sec=10.0, clock=lambda: clock[0])
    wd.beat()                   # enter steady state (grace covered below)
    clock[0] = 9.0
    assert not wd.check()
    clock[0] = 11.0
    assert wd.check() and wd.stalled
    # one stall episode = one increment, however often it's polled
    wd.check()
    assert obs_metrics.counter("watchdog_stall_total").value == 1
    # a beat clears the stall
    wd.beat(2)
    assert not wd.check()
    assert obs_metrics.counter("watchdog_recovered_total").value == 1
    snap = wd.snapshot()
    assert snap["beats"] == 2 and not snap["stalled"]
    with pytest.raises(ValueError):
        Watchdog(stall_sec=0)


def test_watchdog_bringup_grace_before_first_beat():
    """Until the first beat the deadline is stall_sec * grace_factor:
    first-compile bring-up must not read as a stall (a liveness
    supervisor would restart-loop), but a HUNG bring-up still does."""
    obs_metrics.reset_registry()
    clock = [0.0]
    wd = Watchdog(stall_sec=10.0, grace_factor=3.0, clock=lambda: clock[0])
    clock[0] = 25.0             # past stall_sec, inside the grace window
    assert not wd.check()
    clock[0] = 31.0             # past the grace deadline: genuinely hung
    assert wd.check()
    assert obs_metrics.counter("watchdog_stall_total").value == 1
    # after the first beat the plain deadline applies
    wd2 = Watchdog(stall_sec=10.0, grace_factor=3.0, clock=lambda: clock[0])
    wd2.beat()
    clock[0] += 11.0
    assert wd2.check()


def test_watchdog_flips_healthz_to_503(clean_status):
    obs_metrics.reset_registry()
    clock = [0.0]
    wd = Watchdog(stall_sec=5.0, clock=lambda: clock[0])
    wd.beat()               # steady state; plain deadline applies
    status = obs_server.RunStatus("run-3", "changedetection", watchdog=wd)
    srv = obs_server.start_ops_server(0, status, host="127.0.0.1")
    try:
        assert _get(srv.port, "/healthz")[0] == 200
        clock[0] = 6.0      # simulated stall: batch deadline exceeded
        code, body = _get(srv.port, "/healthz")
        assert (code, body) == (503, b"stalled\n")
        assert obs_metrics.counter("watchdog_stall_total").value == 1
        assert not json.loads(_get(srv.port, "/progress")[1])["healthy"]
        wd.beat()           # progress resumes -> healthy again
        assert _get(srv.port, "/healthz")[0] == 200
    finally:
        srv.close()


def test_watchdog_throughput_drop_events():
    obs_metrics.reset_registry()
    clock = [0.0]
    wd = Watchdog(stall_sec=1000.0, clock=lambda: clock[0])
    # steady cadence: 1 beat/sec for 20s, then a 5x slowdown
    for i in range(20):
        clock[0] = float(i)
        wd.beat()
    assert obs_metrics.counter("watchdog_throughput_drop_total").value == 0
    for i in range(6):
        clock[0] = 20.0 + 5.0 * (i + 1)
        wd.beat()
    assert obs_metrics.counter("watchdog_throughput_drop_total").value >= 1
    snap = wd.snapshot()
    assert snap["throughput_drops"], snap
    ev = snap["throughput_drops"][0]
    assert ev["recent_per_sec"] < ev["baseline_per_sec"]


# ---------------------------------------------------------------------------
# Run-correlated JSON logs
# ---------------------------------------------------------------------------

def test_jsonlog_formatter_carries_run_context(clean_status):
    jsonlog.set_run_context(run_id="run-x", process_index=3)
    rec = logging.LogRecord("firebird.pyccd", logging.WARNING, __file__, 1,
                            "chip (%d,%d) failed", (3, 4), None)
    line = json.loads(jsonlog.JsonFormatter().format(rec))
    assert line["message"] == "chip (3,4) failed"
    assert line["level"] == "WARNING"
    assert line["logger"] == "firebird.pyccd"
    assert line["run_id"] == "run-x" and line["process_id"] == 3
    assert line["host"] == jsonlog.HOST and line["pid"]
    jsonlog.clear_run_context()
    line = json.loads(jsonlog.JsonFormatter().format(rec))
    assert line["run_id"] is None and line["process_id"] is None


def test_configure_swaps_formatter_on_env(monkeypatch):
    import firebird_tpu.obs as obs

    root = logging.getLogger("firebird")
    monkeypatch.setenv("FIREBIRD_LOG_FORMAT", "json")
    monkeypatch.setattr(obs, "_configured", False)
    obs.configure()
    assert all(isinstance(h.formatter, jsonlog.JsonFormatter)
               for h in root.handlers)
    # flipping back restores the ISO text format for later tests
    monkeypatch.delenv("FIREBIRD_LOG_FORMAT")
    monkeypatch.setattr(obs, "_configured", False)
    obs.configure()
    assert not any(isinstance(h.formatter, jsonlog.JsonFormatter)
                   for h in root.handlers)


def test_new_run_ids_are_unique():
    ids = {jsonlog.new_run_id() for _ in range(64)}
    assert len(ids) == 64


def test_tracer_carries_run_id():
    t = tracing.start(run_id="run-y")
    try:
        with tracing.span("fetch"):
            pass
    finally:
        tracing.stop()
    trace = t.to_chrome_trace()
    assert trace["otherData"]["run_id"] == "run-y"
    obs_report.validate_trace(trace)


# ---------------------------------------------------------------------------
# Multi-host report aggregation
# ---------------------------------------------------------------------------

def _host_report(host, *, chips, fetch_obs, queue_depth, elapsed):
    reg = obs_metrics.MetricsRegistry()
    reg.counter("chips_detected").inc(chips)
    reg.gauge("store_queue_depth").set(queue_depth)
    reg.gauge("stream_updated").set(chips)
    h = reg.histogram("pipeline_fetch_seconds")
    for v in fetch_obs:
        h.observe(v)
    t = tracing.Tracer()
    with t.span("fetch"):
        pass
    return obs_report.build_report(
        registry=reg, tracer=t,
        run={"kind": "changedetection", "run_id": "fleet-1", "host": host,
             "process_id": int(host[-1]), "chips": chips},
        run_counters={"chips": chips, "elapsed_sec": elapsed,
                      "chips_per_sec": chips / elapsed})


def test_merge_reports_policy():
    r0 = _host_report("h0", chips=4, fetch_obs=[0.01, 0.02],
                      queue_depth=5, elapsed=10.0)
    r1 = _host_report("h1", chips=6, fetch_obs=[0.04, 0.08],
                      queue_depth=2, elapsed=8.0)
    fleet = obs_report.merge_reports([r0, r1])
    obs_report.validate_report(fleet)
    # counters sum
    assert fleet["metrics"]["counters"]["chips_detected"] == 10
    # gauges per declared policy: queue depth max, stream_* sum
    assert fleet["metrics"]["gauges"]["store_queue_depth"] == 5
    assert fleet["metrics"]["gauges"]["stream_updated"] == 10
    # histogram buckets merge; stats recompute over the union
    h = fleet["metrics"]["histograms"]["pipeline_fetch_seconds"]
    assert h["count"] == 4
    assert h["min"] == 0.01 and h["max"] == 0.08
    assert h["sum"] == pytest.approx(0.15)
    assert h["min"] <= h["p50"] <= h["p99"] <= h["max"]
    # spans aggregate
    assert fleet["spans"]["fetch"]["count"] == 2
    # run_counters sum; rates recompute against fleet-max elapsed
    rc = fleet["run_counters"]
    assert rc["chips"] == 10 and rc["elapsed_sec"] == 10.0
    assert rc["chips_per_sec"] == pytest.approx(1.0)
    # fleet identity block
    assert fleet["fleet"]["hosts"] == 2
    assert {h["host"] for h in fleet["fleet"]["host_runs"]} == {"h0", "h1"}


def test_gauge_merge_policy_declarations():
    assert obs_metrics.gauge_merge_policy("stream_updated") == "sum"
    assert obs_metrics.gauge_merge_policy("store_queue_depth") == "max"
    assert obs_metrics.gauge_merge_policy("anything_else") == "max"
    assert obs_metrics.merge_gauge_values("stream_x", [1, 2]) == 3
    assert obs_metrics.merge_gauge_values("depth", [1, 2]) == 2


def test_merge_histogram_snapshots_fallback_without_buckets():
    """Shards from an older schema (no bucket counts) still merge: exact
    count/sum/min/max, percentiles labeled approximate."""
    a = {"count": 2, "sum": 0.2, "mean": 0.1, "min": 0.05, "max": 0.15,
         "p50": 0.1, "p95": 0.15, "p99": 0.15}
    b = {"count": 6, "sum": 1.2, "mean": 0.2, "min": 0.1, "max": 0.4,
         "p50": 0.2, "p95": 0.4, "p99": 0.4}
    m = obs_metrics.merge_histogram_snapshots([a, b])
    assert m["count"] == 8 and m["min"] == 0.05 and m["max"] == 0.4
    assert m["percentiles_approximate"]
    assert m["p50"] == pytest.approx((0.1 * 2 + 0.2 * 6) / 8)
    assert obs_metrics.merge_histogram_snapshots(
        [{"count": 0}, {"count": 0}]) == {"count": 0}


def test_fleet_shard_write_and_merge(tmp_path):
    path = str(tmp_path / "obs_report.json")
    assert obs_report.shard_report_path(path, 1).endswith(
        "obs_report.host1.json")
    for i, chips in enumerate((4, 6)):
        rep = _host_report(f"h{i}", chips=chips, fetch_obs=[0.01],
                           queue_depth=i, elapsed=5.0)
        with open(obs_report.shard_report_path(path, i), "w") as f:
            json.dump(rep, f)
    merged = obs_report.merge_fleet_report(path, 2, timeout=1.0)
    assert merged is not None
    on_disk = json.load(open(path))
    assert on_disk["metrics"]["counters"]["chips_detected"] == 10
    assert on_disk["fleet"]["hosts"] == 2
    assert on_disk["fleet"]["expected_hosts"] == 2
    assert "missing" not in on_disk["fleet"]
    # load_fleet_report prefers the merged file...
    assert obs_report.load_fleet_report(str(tmp_path))["fleet"]["hosts"] == 2
    # ...and falls back to merging shards when it is gone
    (tmp_path / "obs_report.json").unlink()
    fallback = obs_report.load_fleet_report(str(tmp_path))
    assert fallback["metrics"]["counters"]["chips_detected"] == 10


def test_clear_stale_artifacts_scoped_per_process(tmp_path, monkeypatch):
    """Reused artifact dirs (rolling soak): each process removes its OWN
    stale shard at run start — and process 0 the stale merged report —
    so a previous run's shards can never satisfy the merge wait.  A peer
    host's shard is never touched (it cleans its own at its start)."""
    import os

    cfg = Config(store_backend="sqlite", store_path=str(tmp_path / "fb.db"))
    path = obs_report.run_report_path(cfg)
    shard0 = obs_report.shard_report_path(path, 0)
    shard1 = obs_report.shard_report_path(path, 1)
    for p in (path, shard0, shard1):
        with open(p, "w") as f:
            f.write("{}")
    monkeypatch.setattr(obs_report, "_process_info", lambda: (2, 0))
    obs_report.clear_stale_artifacts(cfg)
    assert not os.path.exists(path) and not os.path.exists(shard0)
    assert os.path.exists(shard1)
    monkeypatch.setattr(obs_report, "_process_info", lambda: (2, 1))
    obs_report.clear_stale_artifacts(cfg)
    assert not os.path.exists(shard1)
    # single-process runs leave everything alone
    with open(path, "w") as f:
        f.write("{}")
    monkeypatch.setattr(obs_report, "_process_info", lambda: (1, 0))
    obs_report.clear_stale_artifacts(cfg)
    assert os.path.exists(path)


def test_start_ops_tears_down_on_bind_failure(clean_status, monkeypatch):
    """A failed --ops-port bind must not leak the watchdog thread or the
    global run status past the raise (nothing else would clean them)."""
    import socket

    from firebird_tpu.driver import core

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    # Same exact address the server will bind: identical addr:port always
    # conflicts (a wildcard-vs-specific pair would not on Linux when both
    # sides set SO_REUSEADDR, as OpsServer does).
    monkeypatch.setenv("FIREBIRD_OPS_HOST", "127.0.0.1")
    cfg = Config(store_backend="memory", ops_port=port, stall_sec=60.0)
    try:
        with pytest.raises(OSError):
            core.start_ops(cfg, "rid", "test", chips_total=1,
                           counters=obs_metrics.Counters(), run_block={})
        assert obs_server.current() is None
        assert jsonlog.get_run_context()["run_id"] is None
    finally:
        blocker.close()


def test_merge_fleet_report_tolerates_missing_host(tmp_path):
    path = str(tmp_path / "obs_report.json")
    rep = _host_report("h0", chips=4, fetch_obs=[0.01], queue_depth=0,
                       elapsed=5.0)
    with open(obs_report.shard_report_path(path, 0), "w") as f:
        json.dump(rep, f)
    merged = obs_report.merge_fleet_report(path, 2, timeout=0.3,
                                           poll_sec=0.05)
    assert merged["fleet"]["hosts"] == 1
    assert merged["fleet"]["missing"] == [1]
    assert obs_report.merge_fleet_report(
        str(tmp_path / "empty" / "obs_report.json"), 2, timeout=0.1,
        poll_sec=0.05) is None
    # A host that outlived process 0's merge wait writes its shard late:
    # load_fleet_report must re-merge from the shards rather than serve
    # the incomplete merged file forever.
    late = _host_report("h1", chips=6, fetch_obs=[0.02], queue_depth=1,
                        elapsed=7.0)
    with open(obs_report.shard_report_path(path, 1), "w") as f:
        json.dump(late, f)
    reconciled = obs_report.load_fleet_report(str(tmp_path))
    assert reconciled["fleet"]["hosts"] == 2
    assert reconciled["run_counters"]["chips"] == 10


# ---------------------------------------------------------------------------
# Driver integration: live surface during a real (synthetic) run
# ---------------------------------------------------------------------------

@pytest.mark.slow  # ~24s full driver run under a live ops server; `make obs-smoke` polls the same /healthz /readyz /metrics /progress surface mid-run, and the handler unit rungs above stay in tier-1
def test_driver_serves_ops_surface_during_run(tmp_path):
    """While batches are in flight the endpoints respond; the /progress
    chip totals agree with the final obs_report.json; and the default
    config binds nothing (covered by test_ops_disabled_by_default)."""
    from firebird_tpu.driver import core
    from firebird_tpu.ingest import SyntheticSource

    from conftest import free_port

    # Same shape/dtype as test_driver.py so the jit cache entry is shared.
    port = free_port()
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"),
                 source_backend="synthetic", chips_per_batch=1,
                 dtype="float64", device_sharding="off", fetch_retries=0,
                 ops_port=port, stall_sec=120.0)
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    result: dict = {}

    def run():
        result["done"] = core.changedetection(
            x=100, y=200, acquired="1995-01-01/1997-06-01", number=2,
            chunk_size=2, cfg=cfg, source=src)

    driver = threading.Thread(target=run)
    driver.start()
    live: dict = {}
    try:
        while driver.is_alive():
            for p in ("/healthz", "/readyz", "/progress", "/metrics"):
                try:
                    live[p] = _get(port, p)
                except Exception:
                    pass
            time.sleep(0.05)   # don't hammer the server during compile
    finally:
        driver.join()
    assert len(result["done"]) == 2
    assert live["/healthz"][0] == 200
    assert live["/readyz"][0] == 200          # reached ready mid-run
    for ln in live["/metrics"][1].decode().splitlines():
        assert PROM_LINE.match(ln), ln
    prog = json.loads(live["/progress"][1])
    rep = json.load(open(tmp_path / "obs_report.json"))
    assert prog["run_id"] == rep["run"]["run_id"]
    assert prog["chips_total"] == rep["run"]["chips"] == 2
    assert prog["chips_done"] <= rep["run_counters"]["chips"] == 2
    # run identity threads through to the report run block
    assert rep["run"]["host"] == jsonlog.HOST
    assert rep["run"]["process_id"] == 0
    # the surface is gone once the run ends — nothing left bound
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=1)
