"""Wire diet (ISSUE 11): device-built designs, all-integer ingress, and
int-coded egress.

Three load-bearing contracts:

1. **Golden egress identity** — draining a batch through the int-coded
   egress path (FIREBIRD_WIRE_EGRESS=1: device pack_egress, depth
   slicing, host decode) writes store rows BYTE-IDENTICAL to the raw
   f32 drain (mirror of the compaction on/off golden test).
2. **Device designs match the host spec** — kernel.device_designs
   reproduces harmonic.design_matrix to f32 tolerance (and the phase
   argument exactly; only trig ulp differs).
3. **No float crosses the wire** — every staged ingress plane and every
   packed egress table is integer-dtyped.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firebird_tpu.ccd import format as ccdformat
from firebird_tpu.ccd import harmonic, kernel, params
from firebird_tpu.driver import core
from firebird_tpu.ingest import SyntheticSource, pack
from firebird_tpu.ingest.packer import PackedChips
from firebird_tpu.obs import Counters
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.store import AsyncWriter, MemoryStore


@pytest.fixture(scope="module")
def batch():
    """2 pixel-sliced chips with breaks (so segment depth varies) plus
    the f32 kernel result — the egress golden surface."""
    src = SyntheticSource(seed=5, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1, change_frac=0.5)
    p = pack([src.chip(100 + 3000 * i, 200) for i in range(2)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :96, :],
                    qas=p.qas[:, :96, :], n_obs=p.n_obs)
    seg = kernel.detect_packed(p, dtype=jnp.float32)
    return p, seg


# ---------------------------------------------------------------------------
# 1. golden: int-coded egress writes byte-identical store rows
# ---------------------------------------------------------------------------

def _drain_to_store(seg, p, egress: str, monkeypatch):
    monkeypatch.setenv("FIREBIRD_WIRE_EGRESS", egress)
    store = MemoryStore(f"wire{egress}")
    writer = AsyncWriter(store)
    try:
        core.drain_batch(seg, p, p.n_chips, writer=writer,
                         counters=Counters(), dtype=jnp.float32)
        writer.flush()
    finally:
        writer.close()
    return store


def test_golden_int_egress_store_rows_identical(batch, monkeypatch):
    """THE acceptance golden: every table row the int-coded drain lands
    equals the raw-f32 drain's row exactly — same keys, same cells."""
    p, seg = batch
    on = _drain_to_store(seg, p, "1", monkeypatch)
    off = _drain_to_store(seg, p, "0", monkeypatch)
    for table in ("chip", "pixel", "segment"):
        rows_on, rows_off = on._tables[table], off._tables[table]
        assert set(rows_on) == set(rows_off), table
        for key in rows_off:
            assert rows_on[key] == rows_off[key], (table, key)
    assert on.count("segment") >= p.n_chips * 96


def test_pack_unpack_roundtrip_bit_exact(batch):
    """pack_egress -> decode_egress reproduces every result field bit
    for bit (at the packed depth), and ships only integer tables."""
    p, seg = batch
    raw = jax.device_get(seg)
    worst = int(raw.n_segments.max())
    s_eff = kernel.egress_bucket(worst, raw.seg_meta.shape[-2])
    tables = jax.device_get(kernel.pack_egress(seg, s_eff))
    assert all(v.dtype.kind in "iu" for v in tables.values()), \
        {k: str(v.dtype) for k, v in tables.items()}
    dec = ccdformat.decode_egress(tables, raw.mask.shape[-1])
    np.testing.assert_array_equal(dec.n_segments, raw.n_segments)
    np.testing.assert_array_equal(dec.procedure, raw.procedure)
    np.testing.assert_array_equal(dec.mask, raw.mask)
    np.testing.assert_array_equal(dec.vario, raw.vario)
    np.testing.assert_array_equal(dec.occupancy, raw.occupancy)
    for f in ("seg_meta", "seg_rmse", "seg_mag", "seg_coef"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dec, f)),
            np.asarray(getattr(raw, f))[:, :, :s_eff], err_msg=f)
        assert getattr(dec, f).dtype == np.float32, f


def test_egress_bucket_depths():
    assert kernel.egress_bucket(1, 10) == 1
    assert kernel.egress_bucket(2, 10) == 2
    assert kernel.egress_bucket(3, 10) == 4
    assert kernel.egress_bucket(7, 10) == 8
    assert kernel.egress_bucket(9, 10) == 10     # capped at capacity
    assert kernel.egress_bucket(0, 10) == 1      # no segments: one slot


def test_chprob_count_coding_is_lossless():
    """Every chprob value the kernel can emit (k/PEEK_SIZE and the
    exact 1.0 of a confirmed break) survives the int coding bit-exactly
    — the coding contract pack_egress's meta column relies on."""
    vals = np.array([k / params.PEEK_SIZE
                     for k in range(params.PEEK_SIZE + 1)] + [1.0, 0.0],
                    np.float32)
    coded = np.rint(vals * params.PEEK_SIZE).astype(np.int32)
    decoded = coded.astype(np.float32) / np.float32(params.PEEK_SIZE)
    np.testing.assert_array_equal(decoded, vals)


# ---------------------------------------------------------------------------
# 2. device-built designs match the host float64 spec
# ---------------------------------------------------------------------------

def test_device_designs_match_host_f32_tol(batch):
    """kernel.device_designs == harmonic.design_matrix to f32 tolerance
    (the satellite contract): the exact-integer phase reduction keeps
    the phase argument bit-identical; only trig evaluation differs, by
    trig ulp."""
    p, _ = batch
    Xs, Xts, ts, valids = kernel.device_designs(
        jnp.asarray(p.dates, jnp.int32), jnp.asarray(p.n_obs, jnp.int32),
        jnp.float32)
    hXs, hXts, hvalid = kernel.prep_batch(p)
    np.testing.assert_allclose(np.asarray(Xs), hXs, atol=3e-6, rtol=3e-6)
    np.testing.assert_allclose(np.asarray(Xts), hXts, atol=3e-6,
                               rtol=3e-6)
    np.testing.assert_array_equal(np.asarray(valids), hvalid)
    np.testing.assert_array_equal(np.asarray(ts)[:, :int(p.n_obs[0])],
                                  p.dates[:, :int(p.n_obs[0])])
    # padding rows zeroed, exactly like build_designs' rule
    T = p.dates.shape[1]
    for c in range(p.n_chips):
        n = int(p.n_obs[c])
        if n < T:
            assert not np.asarray(Xs)[c, n:].any()


def test_device_designs_phase_is_exact():
    """The phase argument (t mod 365.25) is exact integer arithmetic —
    bit-identical to the float64 np.mod for any ordinal day, in f32."""
    days = np.arange(690000, 740000, 367, np.int32)[None]
    n = np.array([days.shape[1]], np.int32)
    # reconstruct the device phase computation
    quarter = np.mod(4 * days.astype(np.int64), 1461)
    dev_phase = quarter.astype(np.float32) * np.float32(0.25)
    host_phase = np.mod(days.astype(np.float64), 365.25)
    np.testing.assert_array_equal(dev_phase[0].astype(np.float64),
                                  host_phase[0])
    del n


def test_wire_detect_matches_host_design_detect(batch):
    """Structural safety: running the kernel with device-built designs
    flips no decisions vs the host-built designs on this workload (the
    trig-ulp perturbation is far inside the decision envelope)."""
    p, seg = batch
    Xs, Xts, valid = kernel.prep_batch(p)
    ref = kernel._detect_batch_core(
        jnp.asarray(Xs, jnp.float32), jnp.asarray(Xts, jnp.float32),
        jnp.asarray(p.dates, jnp.float32), jnp.asarray(valid),
        jnp.asarray(p.spectra), jnp.asarray(p.qas, jnp.int32),
        wcap=kernel.window_cap(p), dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(seg.n_segments),
                                  np.asarray(ref.n_segments))
    np.testing.assert_array_equal(
        np.round(np.asarray(seg.seg_meta)[..., [0, 1, 2, 4, 5]]),
        np.round(np.asarray(ref.seg_meta)[..., [0, 1, 2, 4, 5]]))


# ---------------------------------------------------------------------------
# 3. the wire is all-integer, and the counters see it
# ---------------------------------------------------------------------------

def test_staged_ingress_planes_are_integer(batch):
    p, _ = batch
    args = kernel.wire_args(p)
    dts = [np.dtype(a.dtype) for a in args]
    assert all(d.kind in "iu" for d in dts), dts
    assert dts[0] == np.int32 and dts[1] == np.int32
    assert dts[2] == np.int16
    assert dts[3] == (np.uint8 if kernel.wire_qa8() else np.uint16)


def test_qa8_wire_matches_u16(batch, monkeypatch):
    """The uint8 QA wire is lossless for detection: identical results
    vs the full uint16 plane (triage reads bits 0-5 only)."""
    p, _ = batch
    monkeypatch.setenv("FIREBIRD_WIRE_QA8", "0")
    wide = kernel.detect_packed(p, dtype=jnp.float32)
    monkeypatch.setenv("FIREBIRD_WIRE_QA8", "1")
    narrow = kernel.detect_packed(p, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(narrow.n_segments),
                                  np.asarray(wide.n_segments))
    np.testing.assert_array_equal(np.asarray(narrow.seg_meta),
                                  np.asarray(wide.seg_meta))
    np.testing.assert_array_equal(np.asarray(narrow.mask),
                                  np.asarray(wide.mask))


def test_wire_counters_and_packed_d2h(batch, monkeypatch):
    """wire_h2d_bytes counts the integer staging; wire_d2h_bytes counts
    the PACKED drain — strictly smaller than the raw f32 result."""
    p, seg = batch
    obs_metrics.reset_registry()
    monkeypatch.setenv("FIREBIRD_WIRE_EGRESS", "1")
    staged = core.stage_batch(p, jnp.float32, "off")
    store = MemoryStore("wc")
    writer = AsyncWriter(store)
    try:
        core.drain_batch(seg, p, p.n_chips, writer=writer,
                         counters=Counters(), dtype=jnp.float32)
        writer.flush()
    finally:
        writer.close()
    snap = obs_metrics.get_registry().snapshot()["counters"]
    h2d = snap["wire_h2d_bytes"]
    d2h = snap["wire_d2h_bytes"]
    assert h2d == sum(a.nbytes for a in staged.args)
    raw_bytes = int(sum(np.asarray(v).nbytes for v in
                        jax.tree_util.tree_leaves(jax.device_get(seg))))
    assert 0 < d2h < raw_bytes / 2
    obs_metrics.reset_registry()


def test_f64_drain_keeps_raw_path(monkeypatch):
    """The f64 bit-parity path never routes through the f32 egress
    coding (pack_egress is f32-only by contract)."""
    src = SyntheticSource(seed=3, start="1995-01-01", end="1996-06-01")
    p = pack([src.chip(100, 200)], bucket=32)
    p = PackedChips(cids=p.cids, dates=p.dates,
                    spectra=p.spectra[:, :, :32, :],
                    qas=p.qas[:, :32, :], n_obs=p.n_obs)
    seg = kernel.detect_packed(p, dtype=jnp.float64)
    monkeypatch.setenv("FIREBIRD_WIRE_EGRESS", "1")
    host = core.fetch_results(seg)
    assert np.asarray(host.seg_meta).dtype == np.float64
    np.testing.assert_array_equal(np.asarray(host.n_segments),
                                  np.asarray(seg.n_segments))


def test_warm_avatars_hit_real_dispatch_cache(tmp_path):
    """THE warm-start drift contract for the new signature: an AOT
    compile built from warm_start's avatar dtype tuple must be the
    persistent-cache entry a REAL staged dispatch of the same shape
    deserializes.  Any dtype drift between core.wire_avatar_dtypes and
    kernel.wire_args (e.g. a QA wire change on one side only) fails the
    equality below AND the cache-hit assertion."""
    from firebird_tpu.config import Config

    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cfg = Config(store_backend="memory", source_backend="synthetic",
                 compile_cache=str(tmp_path / "cache"))
    try:
        assert core.setup_compile_cache(cfg) == str(tmp_path / "cache")

        src = SyntheticSource(seed=3, start="1995-01-01",
                              end="1996-01-01")
        p = pack([src.chip(100, 200)], bucket=32)
        p = PackedChips(cids=p.cids, dates=p.dates,
                        spectra=p.spectra[:, :, :16, :],
                        qas=p.qas[:, :16, :], n_obs=p.n_obs)
        args_np = kernel.wire_args(p)
        # the one-definition contract: avatar dtypes == staged dtypes
        assert tuple(np.dtype(a.dtype) for a in args_np) \
            == tuple(np.dtype(d) for d in core.wire_avatar_dtypes())

        avatars = tuple(jax.ShapeDtypeStruct(a.shape, d)
                        for a, d in zip(args_np,
                                        core.wire_avatar_dtypes()))
        kernel.aot_compile(avatars, dtype=jnp.float32,
                           wcap=kernel.window_cap(p), sensor=p.sensor)
        assert os.listdir(cfg.compile_cache)       # AOT entry written
        jax.clear_caches()                         # force the cache path
        obs_metrics.reset_registry()
        seg = kernel.detect_packed(p, dtype=jnp.float32)
        assert np.asarray(seg.n_segments).shape == (1, 16)  # ran
        snap = obs_metrics.get_registry().snapshot()
        assert snap["counters"].get("compile_cache_hits", 0) > 0, \
            snap["counters"]
    finally:
        obs_metrics.reset_registry()
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          old_min)
