"""Ingest tests: sources, decoding, packing, oracle bridge."""

import base64
import datetime

import numpy as np
import pytest

from firebird_tpu.ccd import detect, params
from firebird_tpu.ingest import (ChipmunkSource, FileSource, SyntheticSource,
                                 pack, pixel_timeseries)
from firebird_tpu.ingest.packer import CHIP_SIDE, PIXELS, QA_FILL_PACKED, bucket_capacity
from firebird_tpu.ingest.sources import ARD_UBIDS, decode_raster


@pytest.fixture(scope="module")
def source():
    return SyntheticSource(seed=3, start="1995-01-01", end="2001-01-01")


@pytest.fixture(scope="module")
def chipdata(source):
    return source.chip(-543585, 2378805)


def test_synthetic_chip_shapes(chipdata):
    T = chipdata.dates.shape[0]
    assert T > 100
    assert chipdata.spectra.shape == (7, T, 100, 100)
    assert chipdata.qas.shape == (T, 100, 100)
    assert np.all(np.diff(chipdata.dates) > 0)


def test_synthetic_deterministic(source):
    a = source.chip(100, 200)
    b = SyntheticSource(seed=3, start="1995-01-01", end="2001-01-01").chip(100, 200)
    assert np.array_equal(a.spectra, b.spectra)
    assert np.array_equal(a.qas, b.qas)


def test_acquired_range_filters(source):
    c = source.chip(0, 0, acquired="1996-01-01/1998-01-01")
    import datetime
    lo = datetime.date(1996, 1, 1).toordinal()
    hi = datetime.date(1998, 1, 1).toordinal()
    assert c.dates.min() >= lo and c.dates.max() < hi


def test_acquired_window_half_open_partition(tmp_path):
    """The _slice_acquired boundary contract (streamops regression):
    ``[start, end)`` across every source, so adjacent windows PARTITION
    an archive — an observation dated exactly on the boundary lands in
    the later window, never in both (double-delivery) or neither
    (skip).  The acquisition watcher's cursor depends on this."""
    from firebird_tpu.ingest.packer import ChipData

    t = np.array([datetime.date(1999, 6, d).toordinal()
                  for d in (1, 9, 17, 25)], np.int64)
    rng = np.random.default_rng(5)
    spectra = rng.integers(0, 4000, (7, 4, CHIP_SIDE, CHIP_SIDE),
                           dtype=np.int16)
    qas = np.zeros((4, CHIP_SIDE, CHIP_SIDE), np.uint16)
    fs = FileSource(str(tmp_path))
    fs.save_chip(ChipData(cx=0, cy=0, dates=t, spectra=spectra, qas=qas))
    # 1999-06-17 is EXACTLY the boundary of these adjacent windows
    first = fs.chip(0, 0, acquired="1999-06-01/1999-06-17")
    second = fs.chip(0, 0, acquired="1999-06-17/1999-07-01")
    assert list(first.dates) == list(t[:2])
    assert list(second.dates) == list(t[2:])
    # partition: no overlap, no gap — together they are the archive
    both = np.concatenate([first.dates, second.dates])
    assert np.array_equal(both, t)
    assert np.array_equal(
        np.concatenate([first.spectra, second.spectra], axis=1), spectra)


def test_pack_shapes_and_padding(chipdata, source):
    other = source.chip(-540585, 2378805)
    p = pack([chipdata, other], bucket=64)
    assert p.n_chips == 2
    cap = bucket_capacity(chipdata.dates.shape[0], 64, 0)
    assert p.capacity == cap
    assert p.spectra.shape == (2, 7, PIXELS, cap)
    assert p.qas.shape == (2, PIXELS, cap)
    # Padding is QA-fill + FILL_VALUE so the kernel treats it as fill data.
    T = int(p.n_obs[0])
    if cap > T:
        assert np.all(p.qas[0, :, T:] == QA_FILL_PACKED)
        assert np.all(p.spectra[0, :, :, T:] == params.FILL_VALUE)


def test_pixel_coords(chipdata):
    p = pack([chipdata])
    xy = p.pixel_coords(0)
    assert xy.shape == (PIXELS, 2)
    assert tuple(xy[0]) == (-543585, 2378805)           # UL pixel
    assert tuple(xy[1]) == (-543585 + 30, 2378805)      # one col east
    assert tuple(xy[100]) == (-543585, 2378805 - 30)    # one row south
    assert tuple(xy[-1]) == (-543585 + 99 * 30, 2378805 - 99 * 30)


def test_pixel_timeseries_feeds_oracle(chipdata):
    """The packed batch round-trips into the per-pixel detect() contract."""
    p = pack([chipdata])
    ts = pixel_timeseries(p, 0, 4242)
    assert set(ts) == {"dates", "blues", "greens", "reds", "nirs", "swir1s",
                       "swir2s", "thermals", "qas"}
    res = detect(**ts)
    assert res["procedure"] == "standard"
    assert len(res["change_models"]) >= 1


def test_file_source_roundtrip(tmp_path, chipdata, source):
    fs = FileSource(str(tmp_path))
    fs.save_chip(chipdata)
    fs.save_aux(chipdata.cx, chipdata.cy, source.aux(chipdata.cx, chipdata.cy))
    c2 = fs.chip(chipdata.cx, chipdata.cy)
    assert np.array_equal(c2.spectra, chipdata.spectra)
    aux = fs.aux(chipdata.cx, chipdata.cy)
    assert aux["dem"].shape == (100, 100)
    assert set(np.unique(aux["trends"])) <= set(range(1, 9))


def test_chipmunk_source_decodes_and_aligns():
    """Fake Chipmunk: every spectral band present on two dates, QA on three;
    alignment keeps the two common dates.  Wire format matches
    test/data/chip_response.json (base64 LE int16, 20000 bytes)."""
    def raster_b64(value, dtype=np.int16):
        a = np.full((100, 100), value, dtype=dtype)
        return base64.b64encode(a.tobytes()).decode()

    dates = ["1999-01-01", "1999-02-02", "1999-03-03"]

    def fake_get(url):
        assert "/chips?" in url
        from urllib.parse import parse_qs, urlparse
        q = parse_qs(urlparse(url).query)
        ubid = q["ubid"][0]
        if not ubid.startswith("le07"):
            return []  # only one platform has data
        if ubid == "le07_pixelqa":
            return [{"x": -100, "y": 100, "acquired": f"{d}T00:00:00Z",
                     "data": raster_b64(2, np.uint16), "ubid": ubid}
                    for d in dates]
        return [{"x": -100, "y": 100, "acquired": f"{d}T00:00:00Z",
                 "data": raster_b64(777), "ubid": ubid}
                for d in dates[:2]]

    src = ChipmunkSource("http://chipmunk/ard", http_get=fake_get)
    c = src.chip(-100, 100, "1999-01-01/2000-01-01")
    assert c.dates.shape[0] == 2  # only dates where all bands aligned
    assert c.dates[0] == datetime.date(1999, 1, 1).toordinal()
    assert np.all(c.spectra == 777)
    assert np.all(c.qas == 2)


def test_decode_raster_wire_format():
    a = (np.arange(10000, dtype=np.int16) - 5000).reshape(100, 100)
    rec = {"data": base64.b64encode(a.astype("<i2").tobytes()).decode()}
    out = decode_raster(rec)
    assert np.array_equal(out, a)


def test_ubid_coverage():
    # 7 spectral bands + QA, 4 platforms each.
    assert set(ARD_UBIDS) == {"blues", "greens", "reds", "nirs", "swir1s",
                              "swir2s", "thermals", "qas"}
    for v in ARD_UBIDS.values():
        assert len(v) == 4


def test_pack_warns_on_truncation():
    """An archive longer than max_obs loses its newest acquisitions —
    pack must say so (the driver's default FIREBIRD_MAX_OBS=512 vs a
    ~1800-acquisition full Landsat archive is a realistic silent-loss
    footgun otherwise)."""
    import logging

    from firebird_tpu.ingest import SyntheticSource, pack

    src = SyntheticSource(seed=1, start="1995-01-01", end="1999-01-01")
    chip = src.chip(100, 200)
    records: list = []
    h = logging.Handler()
    h.emit = records.append
    log = logging.getLogger("firebird.timeseries")
    log.addHandler(h)
    try:
        p = pack([chip], bucket=32, max_obs=64)
        assert p.spectra.shape[-1] == 64
        assert any("DROPPED" in r.getMessage() for r in records)
        records.clear()
        pack([chip], bucket=32)              # uncapped: no warning
        assert not records
    finally:
        log.removeHandler(h)
