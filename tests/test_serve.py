"""Serving layer: two-tier cache + invalidation, single-flight
coalescing, admission control, degraded mode, the HTTP query API, and
read-under-write consistency against a live writer (firebird_tpu.serve;
docs/SERVING.md)."""

import concurrent.futures
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from firebird_tpu import grid, products
from firebird_tpu.config import Config
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.retry import CircuitBreaker
from firebird_tpu.serve import api as serve_api
from firebird_tpu.serve import cache as serve_cache
from firebird_tpu.serve import flight as serve_flight
from firebird_tpu.store import AsyncWriter, open_store

# The chip containing projection point (100, 200) — any real grid cell
# works; this one matches the smoke tools.
CX, CY = (int(v) for v in grid.snap(100, 200)["chip"]["proj-pt"])
DATE = "1996-01-01"


@pytest.fixture
def fresh_metrics():
    """Serve counters are asserted absolutely in several tests; give each
    its own registry (the suite-wide pattern, tests/test_obs.py)."""
    obs_metrics.reset_registry()
    yield
    obs_metrics.reset_registry()


def seg_frame(cx=CX, cy=CY, chprob=1.0, n=3):
    """A tiny segment frame for chip (cx, cy): n pixels, one row each."""
    return {
        "cx": [cx] * n, "cy": [cy] * n,
        "px": [cx + 30 * i for i in range(n)],
        "py": [cy - 30] * n,
        "sday": ["1995-01-01"] * n, "eday": ["1999-01-01"] * n,
        "bday": ["1997-06-01"] * n, "chprob": [chprob] * n,
        "curqa": [4, 8, 4][:n] + [4] * max(n - 3, 0),
        "rfrawp": [None] * n,
    }


def make_service(store=None, **kw):
    store = store or open_store("memory", "", "t")
    cfg = Config(store_backend="memory")
    return serve_api.ServeService(store, cfg, **kw), store


# ---------------------------------------------------------------------------
# Cache: LRU, spill, generations
# ---------------------------------------------------------------------------

def test_lru_eviction_order(fresh_metrics):
    c = serve_cache.LRUCache(max_entries=2)
    c.put(("a",), 1)
    c.put(("b",), 2)
    assert c.get(("a",)) == 1          # touches a -> b becomes LRU
    c.put(("c",), 3)                   # evicts b
    assert c.get(("b",)) is None
    assert c.get(("a",)) == 1 and c.get(("c",)) == 3
    assert obs_metrics.counter("serve_cache_evictions").value == 1
    assert obs_metrics.counter("serve_cache_misses").value == 1
    assert obs_metrics.counter("serve_cache_hits").value == 3
    with pytest.raises(ValueError):
        serve_cache.LRUCache(max_entries=0)


def test_disk_spill_round_trip(tmp_path, fresh_metrics):
    c = serve_cache.LRUCache(max_entries=1, spill_dir=str(tmp_path))
    arr = np.arange(6, dtype=np.int32)
    c.put(("raster",), arr)
    c.put(("frame",), {"px": [1, 2], "sday": ["1995-01-01", "1995-01-01"]})
    # raster was evicted to disk; reading it promotes it back (and
    # evicts the frame, which spills in turn)
    got = c.get(("raster",))
    assert isinstance(got, np.ndarray) and (got == arr).all()
    got = c.get(("frame",))
    assert got == {"px": [1, 2], "sday": ["1995-01-01", "1995-01-01"]}
    assert obs_metrics.counter("serve_cache_disk_hits").value == 2
    assert obs_metrics.counter("serve_cache_spills").value >= 2


def test_generations_bump_per_chip_and_table():
    g = serve_cache.StoreGenerations()
    assert g.gen("segment", 1, 2) == 0
    g.bump_frame("segment", {"cx": [1, 1, 5], "cy": [2, 2, 6]})
    assert g.gen("segment", 1, 2) == 1
    assert g.gen("segment", 5, 6) == 1
    assert g.gen("segment", 9, 9) == 0
    # non-chip tables (tile: the trained model) bump table-wide
    g.bump_frame("tile", {"tx": [7], "ty": [8], "name": ["rf"]})
    assert g.table_gen("tile") == 1
    # table-wide bumps fold into every chip's generation for that table
    g.bump_table("segment")
    assert g.gen("segment", 9, 9) == 1


def test_watched_store_invalidates_serve_cache(fresh_metrics):
    svc, store = make_service()
    watched = svc.watched_store()
    watched.write("segment", seg_frame(chprob=0.0))
    first = svc.segments(CX, CY)
    assert first["chprob"] == [0.0] * 3
    assert svc.segments(CX, CY) is first          # cached (same object)
    # a live run rewriting the chip through the watched store must
    # invalidate: the next read sees the new rows, not the cache
    watched.write("segment", seg_frame(chprob=1.0))
    assert svc.segments(CX, CY)["chprob"] == [1.0] * 3


# ---------------------------------------------------------------------------
# Flight: coalescing, admission, deadline
# ---------------------------------------------------------------------------

def test_single_flight_coalesces(fresh_metrics):
    import time

    sf = serve_flight.SingleFlight()
    calls = []

    def compute():
        # The leader holds the flight open until all three followers
        # have provably coalesced (the counter increments before each
        # blocks on the flight) — otherwise a fast compute closes the
        # window before the followers arrive and the test races.
        calls.append(1)
        deadline = time.monotonic() + 10
        while (obs_metrics.counter("serve_coalesced_waits").value < 3
               and time.monotonic() < deadline):
            time.sleep(0.002)
        return "value"

    with concurrent.futures.ThreadPoolExecutor(4) as ex:
        results = [f.result() for f in
                   [ex.submit(sf.do, "k", compute) for _ in range(4)]]
    assert results == ["value"] * 4
    assert len(calls) == 1
    assert obs_metrics.counter("serve_coalesced_waits").value == 3
    # the flight deregisters on completion: a LATER call computes fresh
    assert sf.do("k", compute) == "value"
    assert len(calls) == 2


def test_single_flight_shares_leader_error():
    sf = serve_flight.SingleFlight()
    gate = threading.Barrier(2, timeout=10)

    def boom():
        raise RuntimeError("leader failed")

    def request():
        gate.wait()
        return sf.do("k", boom)

    with concurrent.futures.ThreadPoolExecutor(2) as ex:
        futs = [ex.submit(request) for _ in range(2)]
        for f in futs:
            with pytest.raises(RuntimeError, match="leader failed"):
                f.result()


def test_admission_sheds_and_deadlines(fresh_metrics):
    ac = serve_flight.AdmissionControl(max_inflight=1, max_queue=1,
                                       deadline_sec=0.5)
    release = threading.Event()
    inside = threading.Event()

    def hold():
        with ac:
            inside.set()
            release.wait(10)

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    assert inside.wait(5)
    # With the one slot held: of two more arrivals, whichever queues
    # first waits past its deadline (504); the other finds the waiting
    # line full and is shed immediately (429).
    errs: list = []

    def attempt():
        try:
            with ac:
                pass
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=attempt, daemon=True)
               for _ in range(2)]
    for a in threads:
        a.start()
    for a in threads:
        a.join(10)
    release.set()
    t.join(5)
    kinds = {type(e) for e in errs}
    assert kinds == {serve_flight.Overload, serve_flight.DeadlineExceeded}
    shed = next(e for e in errs if isinstance(e, serve_flight.Overload))
    assert shed.retry_after_sec > 0
    assert obs_metrics.counter("serve_rejected_total").value >= 1
    assert obs_metrics.counter("serve_deadline_exceeded_total").value >= 1


def test_admission_zero_queue_still_serves(fresh_metrics):
    """max_queue=0 means 'no waiting line', NOT 'reject everything':
    free execution slots admit immediately without consulting the
    queue bound."""
    ac = serve_flight.AdmissionControl(max_inflight=2, max_queue=0,
                                       deadline_sec=0.2)
    with ac:
        with ac:                       # both slots admit, no queueing
            pass
    # slots full -> the zero-length line sheds instantly
    release = threading.Event()
    inside = threading.Event()

    def hold():
        with ac:
            with ac:
                inside.set()
                release.wait(10)

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    assert inside.wait(5)
    with pytest.raises(serve_flight.Overload):
        with ac:
            pass
    release.set()
    t.join(5)


def test_admission_burst_onto_free_slots_never_sheds(fresh_metrics):
    """max_queue+1 simultaneous arrivals onto an idle controller must
    all admit (the waiting line only judges requests that actually
    wait)."""
    ac = serve_flight.AdmissionControl(max_inflight=8, max_queue=1,
                                       deadline_sec=1.0)
    gate = threading.Barrier(6, timeout=10)
    errs: list = []

    def req():
        gate.wait()
        try:
            with ac:
                pass
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=req, daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert not errs
    assert obs_metrics.counter("serve_rejected_total").value == 0


def test_spill_dir_is_bounded(tmp_path, fresh_metrics):
    """Generation churn must not grow the disk tier without bound: the
    oldest spill files are trimmed past spill_max_files."""
    c = serve_cache.LRUCache(max_entries=1, spill_dir=str(tmp_path),
                             spill_max_files=3)
    for gen in range(10):              # each key unique, as invalidation
        c.put(("raster", gen), np.arange(4, dtype=np.int32))
    files = [n for n in os.listdir(tmp_path) if n.endswith(".npy")]
    assert len(files) <= 3


def test_spill_trim_is_lru_by_access(tmp_path, fresh_metrics):
    """The trim must evict by ACCESS recency, not insert order: a hot
    spilled entry (a pyramid tile the whole fleet revalidates against)
    was *written* first, so insert-order trim would drop it first —
    but every disk hit touches its mtime, so cold churn ages out
    instead."""
    import time

    c = serve_cache.LRUCache(max_entries=1, spill_dir=str(tmp_path),
                             spill_max_files=2)
    hot = ("hot-tile",)
    c.put(hot, np.arange(8, dtype=np.int32))
    c.put(("cold", 0), np.zeros(1, np.int32))   # hot -> disk (oldest write)
    time.sleep(0.05)
    c.put(("cold", 1), np.zeros(1, np.int32))   # cold0 -> disk
    time.sleep(0.05)
    # Disk hit on hot: the promotion TOUCHES its file (newest access)
    # and re-inserting it evicts cold1 -> disk, crossing the bound ->
    # trim fires.  LRU-by-access drops cold0; insert-order would have
    # dropped hot (its write is the oldest on disk).
    got = c.get(hot)
    assert isinstance(got, np.ndarray) and got[3] == 3
    c.clear()
    assert isinstance(c.get(hot), np.ndarray), \
        "hot spill file was evicted by cold churn (insert-order trim)"
    files = [n for n in os.listdir(tmp_path) if n.endswith(".npy")]
    assert len(files) <= 2


# ---------------------------------------------------------------------------
# Service: queries, compute-on-miss, degraded mode
# ---------------------------------------------------------------------------

def test_product_raster_matches_chip_product_and_persists(fresh_metrics):
    svc, store = make_service()
    store.write("segment", seg_frame())
    from firebird_tpu.utils import dates as dt

    got = svc.product_raster("seglength", DATE, CX, CY)
    want = products.chip_product(
        "seglength", dt.to_ordinal(DATE), CX, CY,
        store.read("segment", {"cx": CX, "cy": CY}))
    assert (got == want).all()
    # compute-on-miss persisted the row — the store warms as it serves
    rows = store.read("product", {"name": "seglength", "date": DATE,
                                  "cx": CX, "cy": CY})
    assert rows["cells"] and rows["cells"][0] == want.tolist()
    assert obs_metrics.counter("serve_product_computes").value == 1
    # second call: cache hit, no recompute
    svc.product_raster("seglength", DATE, CX, CY)
    assert obs_metrics.counter("serve_product_computes").value == 1


def test_stored_product_row_wins_over_compute(fresh_metrics):
    svc, store = make_service()
    store.write("segment", seg_frame())
    sentinel = np.full(10000, 7, np.int32)
    cells = np.empty(1, object)
    cells[0] = sentinel.tolist()
    store.write("product", {"name": ["curveqa"], "date": [DATE],
                            "cx": [CX], "cy": [CY], "cells": cells})
    got = svc.product_raster("curveqa", DATE, CX, CY)
    assert (got == 7).all()
    assert obs_metrics.counter("serve_product_computes").value == 0


def test_compute_on_miss_disabled_404s():
    svc, store = make_service(compute_on_miss=False)
    store.write("segment", seg_frame())
    with pytest.raises(serve_api.NotFound):
        svc.product_raster("seglength", DATE, CX, CY)


def test_bad_product_and_date_are_400s():
    svc, _ = make_service()
    with pytest.raises(serve_api.BadRequest):
        svc.product_raster("nope", DATE, CX, CY)
    with pytest.raises(serve_api.BadRequest):
        svc.product_raster("seglength", "not-a-date", CX, CY)


def test_no_segments_is_404():
    svc, _ = make_service()
    with pytest.raises(serve_api.NotFound):
        svc.product_raster("seglength", DATE, CX, CY)


def test_pixel_values(fresh_metrics):
    svc, store = make_service()
    store.write("segment", seg_frame())
    out = svc.pixel(CX + 35.0, CY - 35.0, DATE)
    assert (out["cx"], out["cy"]) == (CX, CY)
    assert out["pixel"] == {"row": 1, "col": 1}
    # pixel (row 1, col 1) has no segment row (frame pixels sit on row 1
    # cols 0..2 at py=cy-30 -> row 1); index math: px=cx+30 -> col 1
    assert out["products"]["curveqa"] == 8
    assert out["products"]["cover"] is None     # no trained model stored
    assert out["products"]["seglength"] > 0


def test_degraded_mode_serves_cache_only(fresh_metrics):
    class Flaky:
        """Store whose reads can be switched to fail."""

        def __init__(self, inner):
            self.inner = inner
            self.broken = False

        def read(self, table, where=None):
            if self.broken:
                raise OSError("store down")
            return self.inner.read(table, where)

        def __getattr__(self, name):
            return getattr(self.inner, name)

    inner = open_store("memory", "", "t")
    inner.write("segment", seg_frame())
    other = seg_frame(cx=CX + 3000)
    inner.write("segment", other)
    flaky = Flaky(inner)
    breaker = CircuitBreaker(1, cooldown_sec=60.0, name="serve-store")
    svc, _ = make_service(store=flaky, breaker=breaker)
    warm = svc.segments(CX, CY)               # cached while healthy
    assert not svc.degraded()

    flaky.broken = True
    # a miss strikes the breaker (threshold 1 -> opens) and maps to 503
    with pytest.raises(serve_api.StoreError):
        svc.segments(CX + 3000, CY)
    assert svc.degraded()
    # cached answers still serve — degraded, not dead
    assert svc.segments(CX, CY) is warm
    # uncached misses now shed with Retry-After instead of hammering
    with pytest.raises(serve_flight.StoreDegraded):
        svc.segments(CX + 6000, CY)
    assert obs_metrics.counter("serve_degraded_misses_total").value == 1

    # the store heals; the breaker's half-open probe readmits
    flaky.broken = False
    breaker._opened_at = -1e9                 # cooldown elapsed (test seam)
    assert svc.segments(CX + 3000, CY)["chprob"] == [1.0] * 3
    assert not svc.degraded()


def test_compute_error_does_not_open_breaker(fresh_metrics, monkeypatch):
    """A deterministic data-dependent COMPUTE failure is that request's
    problem — it must not strike the store breaker and degrade every
    other chip to cache-only serving."""
    svc, store = make_service(
        breaker=CircuitBreaker(1, cooldown_sec=60.0, name="serve-store"))
    store.write("segment", seg_frame())

    def boom(*a, **kw):
        raise RuntimeError("stale rfrawp vs retrained model")

    monkeypatch.setattr(products, "save_chip_raster", boom)
    with pytest.raises(RuntimeError, match="stale rfrawp"):
        svc.product_raster("seglength", DATE, CX, CY)
    assert not svc.degraded()          # threshold is 1: any strike opens
    # the store itself keeps serving
    assert svc.segments(CX, CY)["chprob"] == [1.0] * 3


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------

@pytest.fixture
def served(fresh_metrics):
    svc, store = make_service()
    store.write("segment", seg_frame())
    srv = serve_api.start_serve_server(0, svc, host="127.0.0.1")
    yield svc, store, f"http://127.0.0.1:{srv.port}"
    srv.close()


def _get(base, path):
    try:
        r = urllib.request.urlopen(base + path, timeout=10)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def test_http_endpoints_roundtrip(served):
    svc, store, base = served
    code, body, _ = _get(base, "/healthz")
    assert (code, body) == (200, b"ok\n")
    code, body, _ = _get(base, "/v1/products")
    assert code == 200 and json.loads(body)["products"] == \
        list(products.PRODUCTS)
    code, body, _ = _get(base, f"/v1/segments?cx={CX}&cy={CY}")
    doc = json.loads(body)
    assert code == 200 and doc["n"] == 3
    assert doc["segments"]["curqa"] == [4, 8, 4]
    code, body, _ = _get(base,
                         f"/v1/product/curveqa?cx={CX}&cy={CY}&date={DATE}")
    assert code == 200
    cells = json.loads(body)["cells"]
    assert len(cells) == 10000 and sum(cells) == 16
    # npy format round-trips as a [100, 100] array with chip headers
    import io
    code, body, headers = _get(
        base, f"/v1/product/curveqa?cx={CX}&cy={CY}&date={DATE}&format=npy")
    assert code == 200
    arr = np.load(io.BytesIO(body))
    assert arr.shape == (100, 100) and int(arr.sum()) == 16
    assert headers["X-Firebird-Chip"] == f"{CX},{CY}"
    # /metrics carries the serve family next to the pipeline metrics
    code, body, _ = _get(base, "/metrics")
    assert code == 200
    assert b"firebird_serve_request_seconds" in body
    assert b"firebird_serve_requests_total" in body


def test_http_errors(served):
    _, _, base = served
    code, body, _ = _get(base, "/v1/segments?cx=1")       # missing cy
    assert code == 400 and b"cy" in body
    code, body, _ = _get(base, "/v1/product/nope?cx=1&cy=2&date=" + DATE)
    assert code == 400
    code, body, _ = _get(base, f"/v1/product/ccd?cx=1&cy=2&date={DATE}")
    assert code == 404                                    # no such chip
    code, body, _ = _get(base, "/nope")
    assert code == 404 and b"paths" in body
    assert obs_metrics.counter("serve_errors_total").value >= 3


def test_http_coalesced_cold_miss(served):
    """The acceptance check: 8 concurrent identical cold requests ->
    exactly ONE underlying product computation."""
    svc, store, base = served
    path = f"/v1/product/seglength?cx={CX}&cy={CY}&date={DATE}"
    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        codes = [f.result()[0]
                 for f in [ex.submit(_get, base, path) for _ in range(8)]]
    assert codes == [200] * 8
    assert obs_metrics.counter("serve_product_computes").value == 1


def test_request_trace_ids_survive_coalescing(served):
    """Every /v1 request runs under its own TraceContext and echoes it as
    X-Firebird-Trace — including single-flight followers, which must keep
    their OWN ids (the context is thread-local; only the leader's thread
    runs the fill), not inherit the leader's."""
    svc, store, base = served
    path = f"/v1/product/ccd?cx={CX}&cy={CY}&date={DATE}"
    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        results = [f.result()
                   for f in [ex.submit(_get, base, path) for _ in range(8)]]
    assert [code for code, _, _ in results] == [200] * 8
    assert obs_metrics.counter("serve_product_computes").value == 1
    ids = [headers["X-Firebird-Trace"] for _, _, headers in results]
    assert all(i.startswith("req-") for i in ids)
    assert len(set(ids)) == 8             # coalescing never merges identities
    # the latency histogram picked up request exemplars, not batch ids
    snap = obs_metrics.histogram("serve_request_seconds").snapshot()
    assert any(e["batch"].startswith("req-")
               for e in snap.get("exemplars", ()))


def test_inbound_trace_header_adopted(served):
    """A client's well-formed X-Firebird-Trace is adopted as the
    request's identity (echoed back verbatim — the fleet telemetry
    plane's serve hop); malformed ids are ignored and the handler mints
    its own, and coalesced single-flight followers each keep the id THEY
    sent, never the leader's."""
    svc, store, base = served

    def get(path, trace=None):
        headers = {"X-Firebird-Trace": trace} if trace else {}
        r = urllib.request.urlopen(
            urllib.request.Request(base + path, headers=headers),
            timeout=10)
        return r.status, dict(r.headers)

    path = f"/v1/segments?cx={CX}&cy={CY}"
    code, headers = get(path, trace="scene/LC08_X/aa11")
    assert code == 200
    assert headers["X-Firebird-Trace"] == "scene/LC08_X/aa11"
    # malformed ids (WIRE_RE) must not be adopted: spaces, overlength
    for bad in ("has spaces", "x" * 161):
        code, headers = get(path, trace=bad)
        assert code == 200
        assert headers["X-Firebird-Trace"].startswith("req-")
    # 8 coalesced cold misses, each with its own client id: one compute,
    # every follower's echoed id is the one it sent
    cold = f"/v1/product/ccd?cx={CX}&cy={CY}&date={DATE}"
    sent = [f"client/{i:02d}/ffee" for i in range(8)]
    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        results = [f.result() for f in
                   [ex.submit(get, cold, t) for t in sent]]
    assert [code for code, _ in results] == [200] * 8
    assert obs_metrics.counter("serve_product_computes").value == 1
    assert [h["X-Firebird-Trace"] for _, h in results] == sent


def test_http_degraded_healthz(fresh_metrics):
    svc, store = make_service(
        breaker=CircuitBreaker(1, cooldown_sec=60.0, name="serve-store"))
    svc.breaker.record_failure()              # threshold 1: open
    srv = serve_api.start_serve_server(0, svc, host="127.0.0.1")
    try:
        code, body, _ = _get(f"http://127.0.0.1:{srv.port}", "/healthz")
        assert (code, body) == (200, b"degraded\n")
    finally:
        srv.close()


def test_tile_mosaic_json(served):
    svc, store, base = served
    code, body, _ = _get(
        base, f"/v1/tile/curveqa?bounds={CX + 1},{CY - 1}&date={DATE}"
              "&format=json")
    assert code == 200
    doc = json.loads(body)
    assert doc["shape"] == [100, 100]
    assert doc["ulx"] == CX and doc["uly"] == CY
    flat = np.asarray(doc["cells"], np.int32).ravel()
    assert int(flat[101]) == 8 or int(flat.sum()) == 16


# ---------------------------------------------------------------------------
# Read-under-write: serve reads while an AsyncWriter flushes (sqlite)
# ---------------------------------------------------------------------------

def test_serve_reads_under_async_writer_never_torn(tmp_path):
    """A serve-path read racing a live AsyncWriter upsert must return
    either the pre- or post-upsert rows — never a torn frame mixing the
    two.  SqliteStore commits each frame as one transaction, so readers
    see transaction boundaries, not row-level interleavings."""
    store = open_store("sqlite", str(tmp_path / "rw.db"), "t")
    n = 40
    frames = [seg_frame(chprob=float(v), n=n) for v in (0.0, 1.0)]
    store.write("segment", frames[0])
    stop = threading.Event()
    torn: list = []

    def reader():
        while not stop.is_set():
            got = store.read("segment", {"cx": CX, "cy": CY})
            vals = set(got["chprob"])
            if len(got["px"]) != n or len(vals) != 1 or \
                    vals - {0.0, 1.0}:
                torn.append((len(got["px"]), vals))
                return

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    writer = AsyncWriter(store)
    try:
        for i in range(60):
            writer.write("segment", frames[i % 2], key=(CX, CY))
            if i % 10 == 9:
                writer.flush()
    finally:
        writer.close()
        stop.set()
        for t in threads:
            t.join(10)
        store.close()
    assert not torn, f"torn read frames observed: {torn[:3]}"


def test_service_over_sqlite_sees_writer_results(tmp_path, fresh_metrics):
    """ServeService over a SqliteStore a writer is feeding: reads after
    a flush see the landed rows (the live-run + serving deployment)."""
    store = open_store("sqlite", str(tmp_path / "live.db"), "t")
    svc = serve_api.ServeService(store, Config(store_backend="memory"))
    watched = svc.watched_store()
    writer = AsyncWriter(watched)
    try:
        writer.write("segment", seg_frame(chprob=0.0), key=(CX, CY))
        writer.flush()
        assert svc.segments(CX, CY)["chprob"] == [0.0] * 3
        writer.write("segment", seg_frame(chprob=1.0), key=(CX, CY))
        writer.flush()
        # the AsyncWriter wrote through the watched store -> invalidated
        assert svc.segments(CX, CY)["chprob"] == [1.0] * 3
    finally:
        writer.close()
        store.close()


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------

def test_serve_config_validation():
    assert Config().serve_port == 8080
    cfg = Config.from_env(env={"FIREBIRD_SERVE_PORT": "9001",
                               "FIREBIRD_SERVE_CACHE_ENTRIES": "7",
                               "FIREBIRD_SERVE_CACHE_DIR": "/tmp/x",
                               "FIREBIRD_SERVE_INFLIGHT": "3",
                               "FIREBIRD_SERVE_QUEUE": "5",
                               "FIREBIRD_SERVE_DEADLINE": "2.5"})
    assert (cfg.serve_port, cfg.serve_cache_entries, cfg.serve_cache_dir,
            cfg.serve_inflight, cfg.serve_queue,
            cfg.serve_deadline_sec) == (9001, 7, "/tmp/x", 3, 5, 2.5)
    for bad in ({"serve_port": 0}, {"serve_cache_entries": 0},
                {"serve_inflight": 0}, {"serve_queue": -1},
                {"serve_deadline_sec": 0.0}):
        with pytest.raises(ValueError):
            Config(**bad)
