"""Multi-sensor support: the kernel/packer generic over band layout and chip
geometry (BASELINE.json config #5 — Sentinel-2 12-band, 10 m, 300x300-pixel
chips), with Landsat ARD as the default spec."""

import jax.numpy as jnp
import numpy as np

from firebird_tpu.ccd import kernel, params
from firebird_tpu.ccd.sensor import LANDSAT_ARD, SENTINEL2, chi2_thresholds
from firebird_tpu.ccd.synthetic import means_amps
from firebird_tpu.ingest import SyntheticSource, pack
from firebird_tpu.ingest.packer import PackedChips
from firebird_tpu.parallel import make_mesh
from firebird_tpu.parallel.mesh import detect_sharded


def slice_pixels(p: PackedChips, n: int) -> PackedChips:
    return PackedChips(cids=p.cids, dates=p.dates,
                       spectra=p.spectra[:, :, :n, :], qas=p.qas[:, :n, :],
                       n_obs=p.n_obs, sensor=p.sensor)


def test_sensor_specs_consistent():
    assert LANDSAT_ARD.n_bands == params.NUM_BANDS
    assert LANDSAT_ARD.band_names == params.BAND_NAMES
    assert LANDSAT_ARD.detection_bands == params.DETECTION_BANDS
    assert LANDSAT_ARD.pixels == 10000
    assert SENTINEL2.n_bands == 12
    assert SENTINEL2.pixels == 90000
    assert SENTINEL2.thermal_bands == ()
    # both use 5 detection bands -> identical chi2 thresholds, equal to the
    # module constants pinned for the reference
    chg, out = chi2_thresholds(len(LANDSAT_ARD.detection_bands))
    assert chg == params.CHANGE_THRESHOLD
    assert out == params.OUTLIER_THRESHOLD
    assert chi2_thresholds(5) == (chg, out)
    # detection/tmask roles land on the right wavelengths
    names = SENTINEL2.band_names
    assert [names[i] for i in SENTINEL2.detection_bands] == \
        ["green", "red", "nir", "swir1", "swir2"]
    assert [names[i] for i in SENTINEL2.tmask_bands] == ["green", "swir1"]


def test_means_amps_sized_to_sensor():
    m, a = means_amps(SENTINEL2)
    assert m.shape == (12,) and a.shape == (12,)
    assert np.all(m > 0)
    from firebird_tpu.ccd import synthetic

    m7, a7 = means_amps(LANDSAT_ARD)
    np.testing.assert_array_equal(m7, synthetic.DEFAULT_MEANS)
    np.testing.assert_array_equal(a7, synthetic.DEFAULT_AMPS)


def test_s2_synthetic_chip_shape():
    src = SyntheticSource(seed=3, start="1995-01-01", end="1997-01-01",
                          sensor=SENTINEL2, change_frac=0.0, cloud_frac=0.1)
    c = src.chip(0, 0)
    T = c.dates.shape[0]
    assert c.spectra.shape == (12, T, 300, 300)
    assert c.qas.shape == (T, 300, 300)
    assert c.sensor == SENTINEL2


def test_s2_kernel_detects_step_change():
    """The kernel compiled for the S2 spec finds the break every pixel of a
    whole-chip step change carries, with no thermal screening."""
    src = SyntheticSource(seed=3, start="1995-01-01", end="2000-01-01",
                          sensor=SENTINEL2, change_frac=1.0, cloud_frac=0.1)
    p = slice_pixels(pack([src.chip(0, 0)], bucket=32), 96)
    seg = kernel.detect_packed(p, dtype=jnp.float64)
    nseg = np.asarray(seg.n_segments)[0]
    proc = np.asarray(seg.procedure)[0]
    assert np.all(proc == kernel.PROC_STANDARD)
    assert (nseg >= 2).mean() > 0.9         # break found almost everywhere
    one = kernel.chip_slice(seg, 0, to_host=True)
    rec = kernel.segments_to_records(one, p.dates[0][: int(p.n_obs[0])],
                                     pixel=0, sensor=SENTINEL2)
    assert set(SENTINEL2.band_names) <= set(rec["change_models"][0])
    assert rec["change_models"][0]["swir2"]["rmse"] > 0
    # a confirmed break: first segment has chprob 1
    assert rec["change_models"][0]["change_probability"] == 1.0


def test_s2_result_shapes_follow_band_count():
    src = SyntheticSource(seed=4, start="1995-01-01", end="1997-01-01",
                          sensor=SENTINEL2, change_frac=0.0)
    p = slice_pixels(pack([src.chip(3000, 0)], bucket=32), 16)
    seg = kernel.detect_packed(p, dtype=jnp.float64)
    assert seg.seg_rmse.shape[-1] == 12
    assert seg.seg_coef.shape[-2:] == (12, params.MAX_COEFS)
    assert seg.vario.shape[-1] == 12


def test_s2_pixel_coords_10m():
    src = SyntheticSource(seed=3, start="1995-01-01", end="1996-01-01",
                          sensor=SENTINEL2, change_frac=0.0)
    p = pack([src.chip(0, 30000)], bucket=16)
    xy = p.pixel_coords(0)
    assert xy.shape == (90000, 2)
    assert tuple(xy[0]) == (0, 30000)
    assert tuple(xy[1]) == (10, 30000)          # 10 m pixels
    assert tuple(xy[300]) == (0, 30000 - 10)    # row-major, 300-wide


def test_s2_sharded_over_mesh():
    """Config #5's point: the denser stack shards over the device mesh the
    same way — chip axis split, zero collectives."""
    src = SyntheticSource(seed=5, start="1995-01-01", end="2000-01-01",
                          sensor=SENTINEL2, change_frac=1.0, cloud_frac=0.1)
    chips = [src.chip(3000 * i, 0) for i in range(2)]
    p = slice_pixels(pack(chips, bucket=32), 64)
    mesh = make_mesh(n_devices=2)
    seg = detect_sharded(p, mesh, dtype=jnp.float64)
    nseg = np.asarray(seg.n_segments)
    assert nseg.shape == (2, 64)
    assert (nseg >= 2).mean() > 0.8


def test_mixed_sensor_pack_rejected():
    l = SyntheticSource(seed=1, start="1995-01-01", end="1996-01-01")
    s = SyntheticSource(seed=1, start="1995-01-01", end="1996-01-01",
                        sensor=SENTINEL2)
    try:
        pack([l.chip(0, 0), s.chip(0, 0)])
    except AssertionError as e:
        assert "sensor" in str(e)
    else:
        raise AssertionError("mixed-sensor pack must be rejected")
