"""Observability: per-subsystem logger categories and level config
(log4j.properties:48-53 parity), throughput counters, and the telemetry
layer (span tracer, metrics registry, per-run report artifacts)."""

import json
import logging
import threading

import pytest

from firebird_tpu import obs
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import report as obs_report
from firebird_tpu.obs import tracing


# ---------------------------------------------------------------------------
# Logging (the original obs.py surface, now the package __init__)
# ---------------------------------------------------------------------------

def test_categories_mirror_reference():
    assert set(obs.CATEGORIES) == {
        "ids", "change-detection", "random-forest-training",
        "random-forest-classification", "timeseries", "pyccd"}


def test_logger_namespaced_and_configured():
    log = obs.logger("pyccd")
    assert log.name == "firebird.pyccd"
    root = logging.getLogger("firebird")
    assert root.handlers and not root.propagate


def test_level_env_overrides(monkeypatch):
    monkeypatch.setenv("FIREBIRD_LOG_LEVELS", "ids=DEBUG, pyccd=ERROR")
    monkeypatch.setattr(obs, "_configured", False)
    obs.configure()
    assert logging.getLogger("firebird.ids").getEffectiveLevel() \
        == logging.DEBUG
    assert logging.getLogger("firebird.pyccd").getEffectiveLevel() \
        == logging.ERROR
    # restore: re-run configure with defaults so later tests see INFO
    logging.getLogger("firebird.ids").setLevel(logging.NOTSET)
    logging.getLogger("firebird.pyccd").setLevel(logging.NOTSET)


def test_counters_snapshot_rates():
    c = obs.Counters()
    c.add("chips")
    c.add("pixels", 10000)
    snap = c.snapshot()
    assert snap["chips"] == 1 and snap["pixels"] == 10000
    assert "pixels_per_sec" in snap and snap["elapsed_sec"] >= 0


def test_counters_rate_clock_excludes_preconstruction_idle():
    """*_per_sec divides by ACTIVE run time: the clock starts at the
    first add (or an explicit start()), not at construction — a long
    setup/compile gap before the run must not deflate the rates."""
    import time

    c = obs.Counters()
    time.sleep(0.25)                    # pre-run idle (setup, compile)
    assert c.snapshot() == {"elapsed_sec": 0.0}   # no clock yet, no rates
    c.add("chips", 10)
    snap = c.snapshot()
    # elapsed measures from the first add, not from construction
    assert snap["elapsed_sec"] < 0.2, snap
    assert snap["chips_per_sec"] > 10 / 0.2
    # explicit start() re-bases the clock (drivers call it at the first
    # productive moment)
    c2 = obs.Counters()
    time.sleep(0.1)
    c2.start()
    c2.add("pixels", 100)
    assert c2.snapshot()["elapsed_sec"] < 0.1


# ---------------------------------------------------------------------------
# Span tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_export_roundtrip():
    t = tracing.start()
    try:
        with tracing.span("fetch", chip=(1, 2)):
            with tracing.span("pack", chips=3):
                pass
    finally:
        assert tracing.stop() is t
    trace = json.loads(json.dumps(t.to_chrome_trace()))   # wire round-trip
    obs_report.validate_trace(trace)
    evs = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert set(evs) == {"fetch", "pack"}
    # nesting: the child interval is contained in the parent's, same track
    f, p = evs["fetch"], evs["pack"]
    assert f["tid"] == p["tid"]
    assert f["ts"] <= p["ts"]
    assert p["ts"] + p["dur"] <= f["ts"] + f["dur"] + 1e-3
    # args survive export; non-scalar values stringify
    assert p["args"]["chips"] == 3
    assert f["args"]["chip"] == "(1, 2)"
    # summary table aggregates per name
    s = t.summary()
    assert s["fetch"]["count"] == 1 and s["fetch"]["max_ms"] >= 0


def test_spans_are_thread_aware():
    t = tracing.start()
    try:
        def work():
            with tracing.span("worker"):
                pass
        th = threading.Thread(target=work, name="obs-test-worker")
        with tracing.span("main"):
            th.start()
            th.join()
    finally:
        tracing.stop()
    trace = t.to_chrome_trace()
    tids = {e["name"]: e["tid"] for e in trace["traceEvents"]
            if e["ph"] == "X"}
    assert tids["main"] != tids["worker"]
    meta = {e["args"]["name"] for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "obs-test-worker" in meta


def test_span_noop_when_disabled():
    assert tracing.active() is None
    with tracing.span("fetch") as s:           # records nowhere, raises never
        assert s is tracing._NULL_SPAN


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

def test_histogram_percentiles():
    h = obs_metrics.Histogram("t_seconds")
    for ms in range(1, 101):                   # 1..100 ms, uniform
        h.observe(ms / 1000.0)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["sum"] == pytest.approx(5.05, rel=1e-6)
    assert snap["min"] == 0.001 and snap["max"] == 0.1
    # fixed-bucket interpolation: tolerance is the containing bucket width
    assert snap["p50"] == pytest.approx(0.050, abs=0.015)
    assert snap["p95"] == pytest.approx(0.095, abs=0.01)
    # percentiles never exceed the observed range
    assert snap["min"] <= snap["p99"] <= snap["max"]


def test_histogram_observe_many_matches_observe():
    """Bulk ingestion (the drain thread's occupancy feed) lands the same
    state as per-value observe — identical snapshot, one lock hold."""
    vals = [ms / 1000.0 for ms in range(1, 101)] + [1e6]  # incl. overflow
    one = obs_metrics.Histogram("t_seconds")
    for v in vals:
        one.observe(v)
    bulk = obs_metrics.Histogram("t_seconds")
    bulk.observe_many(vals)
    bulk.observe_many([])                       # no-op, not a crash
    s1, s2 = one.snapshot(), bulk.snapshot()
    assert s1 == pytest.approx(s2)
    assert s2["count"] == len(vals)


def test_histogram_empty_and_overflow():
    h = obs_metrics.Histogram("t_seconds")
    assert h.snapshot() == {"count": 0}
    assert h.quantile(0.5) is None
    h.observe(1e6)                             # beyond the last bucket
    assert h.quantile(0.5) == 1e6              # overflow reports observed max


def test_histogram_quantile_edge_cases():
    # empty: every quantile is None, including the extremes
    h = obs_metrics.Histogram("t_seconds")
    assert h.quantile(0.0) is None and h.quantile(1.0) is None
    # single observation: every quantile IS that observation
    h.observe(0.03)
    for q in (0.0, 0.5, 0.95, 1.0):
        assert h.quantile(q) == pytest.approx(0.03)
    snap = h.snapshot()
    assert snap["count"] == 1
    assert snap["min"] == snap["max"] == pytest.approx(0.03)
    # q=0 / q=1 clamp to the observed range, never the bucket edges
    h2 = obs_metrics.Histogram("t2_seconds")
    for v in (0.012, 0.07, 0.9):
        h2.observe(v)
    assert h2.quantile(0.0) == pytest.approx(0.012)
    assert h2.quantile(1.0) == pytest.approx(0.9)
    assert 0.012 <= h2.quantile(0.5) <= 0.9


def test_reset_registry_isolates_runs():
    """A new driver run must not inherit the previous run's metrics —
    and handles captured from the OLD registry must not leak into the
    new one."""
    reg1 = obs_metrics.reset_registry()
    obs_metrics.counter("chips").inc(7)
    obs_metrics.histogram("pipeline_fetch_seconds").observe(0.5)
    old_counter = obs_metrics.counter("chips")
    reg2 = obs_metrics.reset_registry()
    assert reg2 is obs_metrics.get_registry() and reg2 is not reg1
    # fresh registry: clean slate for the same names
    assert obs_metrics.counter("chips").value == 0
    assert obs_metrics.histogram("pipeline_fetch_seconds").snapshot() \
        == {"count": 0}
    # the old handle still works but writes to the dead registry only
    old_counter.inc()
    assert obs_metrics.counter("chips").value == 0
    assert reg1.counter("chips").value == 8


def test_prometheus_exposition_format():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("chips").inc(5)
    reg.gauge("store_queue_depth").set(3)
    h = reg.histogram("pipeline_fetch_seconds")
    h.observe(0.002)
    h.observe(0.2)
    text = reg.prometheus()
    assert "# TYPE firebird_chips_total counter" in text
    assert "firebird_chips_total 5" in text
    assert "# TYPE firebird_store_queue_depth gauge" in text
    assert "firebird_store_queue_depth 3" in text
    assert "# TYPE firebird_pipeline_fetch_seconds histogram" in text
    assert 'firebird_pipeline_fetch_seconds_bucket{le="+Inf"} 2' in text
    assert "firebird_pipeline_fetch_seconds_count 2" in text
    # cumulative buckets are monotonic
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("firebird_pipeline_fetch_seconds_bucket")]
    assert cums == sorted(cums)


def test_prometheus_help_lines_and_total_guard():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("chips", help="chips drained to the store").inc(2)
    # a counter already named *_total must not become *_total_total
    reg.counter("watchdog_stall_total").inc()
    reg.gauge("store_queue_depth").set(1)
    reg.histogram("pipeline_fetch_seconds").observe(0.01)
    text = reg.prometheus()
    assert "# HELP firebird_chips_total chips drained to the store" in text
    assert "firebird_watchdog_stall_total 1" in text
    assert "firebird_watchdog_stall_total_total" not in text
    # every metric gets a HELP line (declared or derived)
    assert "# HELP firebird_store_queue_depth " in text
    assert "# HELP firebird_pipeline_fetch_seconds " in text
    # _prom_name only suffixes counters
    assert obs_metrics._prom_name("chips", "counter") \
        == "firebird_chips_total"
    assert obs_metrics._prom_name("x_total", "counter") \
        == "firebird_x_total"
    assert obs_metrics._prom_name("chips") == "firebird_chips"


def test_prometheus_exposition_roundtrips_format_regex():
    """Every exposition line is `# HELP|# TYPE ...` or
    `name{labels} value` — the format a scraper actually parses (the
    shared contract regex, also applied by tools/obs_smoke.py)."""
    prom_line = obs_metrics.PROM_LINE_RE
    reg = obs_metrics.MetricsRegistry()
    reg.counter("chips").inc(3)
    reg.counter("watchdog_stall_total")
    reg.gauge("negative").set(-2.5)
    reg.gauge("tiny").set(1e-07)
    h = reg.histogram("pipeline_fetch_seconds")
    for v in (0.0001, 0.02, 4.0, 1e6):
        h.observe(v)
    reg.histogram("empty_seconds")
    lines = reg.prometheus().splitlines()
    assert lines, "exposition must not be empty"
    for ln in lines:
        assert prom_line.match(ln), f"malformed exposition line: {ln!r}"


def test_counter_thread_safety():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("hits")
    n_threads, n_incs = 8, 2000

    def work():
        for _ in range(n_incs):
            c.inc()
    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_incs


def test_metrics_env_gate(monkeypatch):
    reg = obs_metrics.MetricsRegistry()
    monkeypatch.setenv("FIREBIRD_METRICS", "0")
    reg.counter("c").inc()
    reg.gauge("g").set(9)
    reg.histogram("h").observe(1.0)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 0
    assert snap["gauges"]["g"] == 0.0
    assert snap["histograms"]["h"] == {"count": 0}
    monkeypatch.delenv("FIREBIRD_METRICS")
    reg.counter("c").inc()
    assert reg.counter("c").value == 1


def test_registry_once_is_per_registry():
    reg = obs_metrics.reset_registry()
    assert reg.once(("shape", 1)) and not reg.once(("shape", 1))
    assert obs_metrics.reset_registry().once(("shape", 1))


# ---------------------------------------------------------------------------
# Report artifact + driver smoke
# ---------------------------------------------------------------------------

def test_report_build_and_validate(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    reg.counter("chips").inc(2)
    reg.histogram("pipeline_fetch_seconds").observe(0.01)
    t = tracing.Tracer()
    with t.span("fetch"):
        pass
    path = str(tmp_path / "obs_report.json")
    rep = obs_report.write_report(path, registry=reg, tracer=t,
                                  run={"kind": "test"},
                                  run_counters={"chips": 2})
    obs_report.validate_report(json.load(open(path)))
    assert rep["run"]["kind"] == "test"
    assert rep["spans"]["fetch"]["count"] == 1
    with pytest.raises(ValueError):
        obs_report.validate_report({"schema": "bogus"})
    with pytest.raises(ValueError):
        obs_report.validate_trace({"traceEvents": [{"ph": "X"}]})


@pytest.mark.slow
def test_driver_run_emits_report_and_trace(tmp_path):
    """End-to-end: a synthetic changedetection run with tracing on writes
    obs_report.json (all driver stage keys populated) and a valid Chrome
    trace containing the fetch/pack/dispatch/drain spans."""
    from firebird_tpu.config import Config
    from firebird_tpu.driver import core
    from firebird_tpu.ingest import SyntheticSource

    # Same shape/dtype as test_driver.py so the jit cache entry is shared.
    cfg = Config(store_backend="sqlite",
                 store_path=str(tmp_path / "fb.db"),
                 source_backend="synthetic", chips_per_batch=1,
                 dtype="float64", device_sharding="off", fetch_retries=0,
                 trace=str(tmp_path / "trace.json"))
    src = SyntheticSource(seed=9, start="1995-01-01", end="1998-01-01",
                          cloud_frac=0.1)
    done = core.changedetection(x=100, y=200,
                                acquired="1995-01-01/1997-06-01",
                                number=2, chunk_size=2, cfg=cfg, source=src)
    assert len(done) == 2

    trace = json.load(open(tmp_path / "trace.json"))
    rep = json.load(open(tmp_path / "obs_report.json"))
    # the shared obs-smoke contract (same check `make obs-smoke` runs)
    obs_report.validate_driver_artifacts(trace, rep)
    assert rep["run"]["kind"] == "changedetection"
    assert rep["run_counters"]["chips"] == 2
    # spans surfaced in the summary table too
    assert rep["spans"]["dispatch"]["count"] >= 1


def test_memory_store_run_writes_no_report(tmp_path, monkeypatch):
    """Auto mode must not litter artifacts for memory-backed (test) runs."""
    from firebird_tpu.config import Config

    monkeypatch.chdir(tmp_path)
    cfg = Config(store_backend="memory", source_backend="synthetic")
    assert obs_report.run_report_path(cfg) is None
    cfg = Config(store_backend="memory", obs_report=str(tmp_path / "r.json"))
    assert obs_report.run_report_path(cfg) == str(tmp_path / "r.json")
    cfg = Config(store_backend="sqlite", store_path="x/fb.db",
                 obs_report="0")
    assert obs_report.run_report_path(cfg) is None
