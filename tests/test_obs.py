"""Observability: per-subsystem logger categories and level config
(log4j.properties:48-53 parity) + throughput counters."""

import logging

from firebird_tpu import obs


def test_categories_mirror_reference():
    assert set(obs.CATEGORIES) == {
        "ids", "change-detection", "random-forest-training",
        "random-forest-classification", "timeseries", "pyccd"}


def test_logger_namespaced_and_configured():
    log = obs.logger("pyccd")
    assert log.name == "firebird.pyccd"
    root = logging.getLogger("firebird")
    assert root.handlers and not root.propagate


def test_level_env_overrides(monkeypatch):
    monkeypatch.setenv("FIREBIRD_LOG_LEVELS", "ids=DEBUG, pyccd=ERROR")
    monkeypatch.setattr(obs, "_configured", False)
    obs.configure()
    assert logging.getLogger("firebird.ids").getEffectiveLevel() \
        == logging.DEBUG
    assert logging.getLogger("firebird.pyccd").getEffectiveLevel() \
        == logging.ERROR
    # restore: re-run configure with defaults so later tests see INFO
    logging.getLogger("firebird.ids").setLevel(logging.NOTSET)
    logging.getLogger("firebird.pyccd").setLevel(logging.NOTSET)


def test_counters_snapshot_rates():
    c = obs.Counters()
    c.add("chips")
    c.add("pixels", 10000)
    snap = c.snapshot()
    assert snap["chips"] == 1 and snap["pixels"] == 10000
    assert "pixels_per_sec" in snap and snap["elapsed_sec"] >= 0
